#!/usr/bin/env python
"""Mesh-group certification on large virtual meshes (ISSUE 10).

Parent mode spawns one hermetic child per device count (16 and 32 by
default — bigger than the 8-device tier-1 mesh) with
`--xla_force_host_platform_device_count` forced before JAX initializes.
Each child boots a REAL 4-node in-process cluster sharing one ICI domain
(`[mesh] group`), drives PQL through the coordinator's HTTP-facing api
layer, and certifies:

- a mesh-local `Count(Intersect(Row, Row))` executes with EXACTLY one
  compiled dispatch and one blocking host read (plan.STATS counters),
  with exactly one mesh-group dispatch and zero HTTP fallbacks;
- every certified query shape is bit-identical across the mesh-group
  path, the HTTP fan-out path (mesh disabled per node), and a host-side
  truth model (python sets over the imported positions);
- warm per-query wall time for the mesh path vs the HTTP fan-out path
  (`meshN_count_ms` / `httpN_count_ms` — the numbers bench.py records
  as mesh16_count_ms / mesh32_count_ms).

The parent writes MULTICHIP_r06.json; CI uploads it as an artifact.
Run locally: `python tools/mesh_cert.py --out MULTICHIP_r06.json`.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def child(n_devices: int) -> dict:
    from pilosa_tpu.utils.cpuonly import force_cpu

    force_cpu(n_devices)

    import numpy as np

    from pilosa_tpu.exec import meshgroup
    from pilosa_tpu.exec import plan as planmod
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.testing import ClusterHarness

    rng = np.random.default_rng(10)
    n_shards = n_devices * 2 + 1  # deliberately unpadded
    out: dict = {"n_devices": n_devices, "n_shards": n_shards, "nodes": 4}

    # cache_result_mb=0: the cert counter-asserts the DISPATCH shape of
    # repeat queries; a result-cache hit (the intended fast path) would
    # serve them with zero dispatches and certify nothing
    with ClusterHarness(
        4, in_memory=True, mesh_group="cert-ici",
        telemetry_sample_interval=0.0, cache_result_mb=0,
    ) as cluster:
        api = cluster[0].api
        api.create_index("cert")
        api.create_field("cert", "f")
        api.create_field(
            "cert", "v", options={"type": "int", "min": -500, "max": 500}
        )
        cols = {}
        # rows 1/2 drawn from a 4-shard window (dense enough that the
        # certified intersection is nonzero — a trivially-empty result
        # would certify nothing), row 3 over the full column space so
        # every node owns live shards. Volumes stay modest on purpose:
        # the virtual-device collectives schedule 32 participants onto
        # ~2 CI cores, so the cert certifies correctness + counters, not
        # throughput (bench.py owns the numbers).
        window = min(4, n_shards) * SHARD_WIDTH
        for r, hi in ((1, window), (2, window), (3, n_shards * SHARD_WIDTH)):
            c = rng.integers(0, hi, 4000).astype(np.uint64)
            api.import_bits("cert", "f", np.full(len(c), r, np.uint64), c)
            cols[r] = set(c.tolist())
        vcols = np.unique(
            rng.integers(0, n_shards * SHARD_WIDTH, 2000).astype(np.uint64)
        )
        vvals = rng.integers(-500, 501, len(vcols)).astype(np.int64)
        api.import_values("cert", "v", vcols, vvals)

        def set_mesh(on: bool) -> None:
            for node in cluster.nodes:
                node.executor.mesh_min_nodes = 2 if on else 0

        # --- acceptance counters: 1 dispatch + 1 blocking read ----------
        set_mesh(True)
        api.query("cert", "Count(Intersect(Row(f=1), Row(f=2)))")  # warm
        planmod.reset_stats()
        meshgroup.reset_stats()
        (got_i,) = api.query("cert", "Count(Intersect(Row(f=1), Row(f=2)))")
        snap = meshgroup.stats_snapshot()
        out["count_intersect"] = int(got_i)
        out["dispatches"] = planmod.STATS["evals"]
        out["host_reads"] = planmod.STATS["host_reads"]
        out["mesh_dispatches"] = snap["dispatches"]
        out["mesh_local_shards"] = snap["local_shards"]
        out["mesh_fallbacks"] = snap["fallbacks"]
        assert planmod.STATS["evals"] == 1, planmod.STATS
        assert planmod.STATS["host_reads"] == 1, planmod.STATS
        assert snap["dispatches"] == 1 and snap["fallbacks"] == 0, snap
        assert got_i == len(cols[1] & cols[2]), (got_i, len(cols[1] & cols[2]))

        # --- differential equivalence: mesh vs HTTP vs host truth -------
        want_gt = sum(1 for x in vvals if x > 100)
        shapes = [
            ("Count(Intersect(Row(f=1), Row(f=2)))", len(cols[1] & cols[2])),
            ("Count(Union(Row(f=1), Row(f=2)))", len(cols[1] | cols[2])),
            ("Count(Difference(Row(f=1), Row(f=3)))", len(cols[1] - cols[3])),
            ("Count(Xor(Row(f=2), Row(f=3)))", len(cols[2] ^ cols[3])),
            ("Count(Row(v > 100))", want_gt),
        ]
        for pql, truth in shapes:
            set_mesh(True)
            (mesh_r,) = api.query("cert", pql)
            set_mesh(False)
            (http_r,) = api.query("cert", pql)
            assert mesh_r == http_r == truth, (pql, mesh_r, http_r, truth)
        for pql in ("TopN(f, n=3)", "TopN(f, Row(f=2), n=3)"):
            set_mesh(True)
            (mesh_p,) = api.query("cert", pql)
            set_mesh(False)
            (http_p,) = api.query("cert", pql)
            assert [(p.id, p.count) for p in mesh_p] == [
                (p.id, p.count) for p in http_p
            ], (pql, mesh_p, http_p)
        # BSI aggregate shapes (round 11, plane-streamed lowering): mesh
        # == HTTP == host truth, and each warm mesh aggregate is exactly
        # ONE compiled dispatch + ONE scalar-sized blocking host read
        # however many devices the group spans
        want_min = int(min(vvals))
        want_max = int(max(vvals))
        bsi_shapes = [
            ("Sum(field=v)", (int(vvals.sum()), len(vvals))),
            ("Min(field=v)", (want_min, int((vvals == want_min).sum()))),
            ("Max(field=v)", (want_max, int((vvals == want_max).sum()))),
        ]
        for pql, (want_v, want_c) in bsi_shapes:
            set_mesh(True)
            api.query("cert", pql)  # warm: stage + compile
            planmod.reset_stats()
            meshgroup.reset_stats()
            (mesh_vc,) = api.query("cert", pql)
            snap = meshgroup.stats_snapshot()
            assert planmod.STATS["evals"] == 1, (pql, planmod.STATS)
            assert planmod.STATS["host_reads"] == 1, (pql, planmod.STATS)
            assert snap["dispatches"] == 1 and snap["fallbacks"] == 0, (
                pql, snap,
            )
            set_mesh(False)
            (http_vc,) = api.query("cert", pql)
            assert (mesh_vc.value, mesh_vc.count) == (want_v, want_c), (
                pql, mesh_vc, want_v, want_c,
            )
            assert (http_vc.value, http_vc.count) == (want_v, want_c), (
                pql, http_vc,
            )
        # streamed Range count: the traced-predicate program, 1 dispatch
        set_mesh(True)
        api.query("cert", "Count(Row(v > 99))")  # warm the program shape
        planmod.reset_stats()
        (got_r,) = api.query("cert", "Count(Row(v > 100))")
        assert got_r == want_gt, (got_r, want_gt)
        assert planmod.STATS["evals"] == 1, planmod.STATS
        assert planmod.STATS["host_reads"] == 1, planmod.STATS
        out["bsi_shapes"] = len(bsi_shapes) + 1
        out["equivalence_shapes"] = len(shapes) + 2 + len(bsi_shapes) + 1

        # --- warm latency: mesh fold vs HTTP fan-out --------------------
        def median_ms(fn, n: int = 5) -> float:
            ts = []
            for _ in range(n):
                t0 = time.perf_counter()
                fn()
                ts.append((time.perf_counter() - t0) * 1e3)
            ts.sort()
            return ts[len(ts) // 2]

        pql = "Count(Intersect(Row(f=1), Row(f=2)))"
        set_mesh(True)
        api.query("cert", pql)  # warm stacks + compile under this mode
        out["mesh_count_ms"] = round(
            median_ms(lambda: api.query("cert", pql)), 3
        )
        set_mesh(False)
        api.query("cert", pql)
        out["http_count_ms"] = round(
            median_ms(lambda: api.query("cert", pql)), 3
        )
    out["ok"] = True
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", type=int, help="internal: run one device count")
    ap.add_argument(
        "--devices", type=int, nargs="*", default=[16, 32],
        help="virtual device counts to certify (parent mode)",
    )
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args()

    if args.child:
        print(json.dumps(child(args.child)))
        return 0

    report: dict = {"rounds": []}
    ok = True
    for n in args.devices:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", str(n)],
                capture_output=True, text=True, timeout=2400, env=env,
                cwd=REPO_ROOT,
            )
            if proc.returncode != 0:
                ok = False
                report["rounds"].append({
                    "n_devices": n, "ok": False,
                    "tail": (proc.stderr or proc.stdout)[-2000:],
                })
            else:
                report["rounds"].append(
                    json.loads(proc.stdout.strip().splitlines()[-1])
                )
        except Exception as e:  # noqa: BLE001 - report, don't crash CI silently
            ok = False
            report["rounds"].append(
                {"n_devices": n, "ok": False, "tail": f"{type(e).__name__}: {e}"}
            )
    report["ok"] = ok
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
