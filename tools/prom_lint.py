#!/usr/bin/env python
"""Prometheus text-exposition linter for the /metrics endpoint.

Checks the rendered text (not the renderer), so a regression anywhere in
the registry -> exposition path is caught:

* every sample line parses and belongs to a family with exactly ONE
  `# TYPE` declaration, placed before the family's first sample;
* histogram families carry `_bucket`/`_sum`/`_count` series whose
  bucket counts are cumulative and monotone over ascending `le` bounds,
  end in an `+Inf` bucket, and whose `+Inf` count equals `_count`;
* every family maps back to a name declared in `utils/stats.py`
  STAT_NAMES (or a STAT_PREFIXES dynamic family) — a rendered metric
  nothing declared is exactly the silent dashboard rot the registry
  exists to prevent;
* labeled families honor `utils/stats.py` STAT_LABELS: every series of
  a listed family carries EXACTLY the declared label keys (no dropped
  key, no extra key, no unlabeled series mixed in), and a family NOT
  listed renders unlabeled — so a per-index dashboard can trust that
  `sum by (index)` covers the whole family.

`lint(text)` returns a list of error strings (empty = clean); the CLI
reads a file or stdin and exits non-zero on findings. Used by
tools/metrics_smoke.py in CI and by the tier-1 flight-recorder tests.
"""

from __future__ import annotations

import re
import sys
from typing import Dict, List, Optional, Tuple

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$"
)
_LE_RE = re.compile(r'(?:^|,)le="(?P<le>[^"]+)"')
# key="value" pairs; values may contain escaped quotes (the renderer
# escapes \ " and newline per the exposition spec)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="(?:[^"\\]|\\.)*"')

_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _family_of(sample_name: str, histogram_families: set) -> str:
    """Strip the _bucket/_sum/_count suffix when the base is a declared
    histogram family."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in histogram_families:
                return base
    return sample_name


def _strip_le(labels: Optional[str]) -> str:
    if not labels:
        return ""
    return ",".join(
        p for p in labels.split(",") if not p.startswith("le=")
    )


def _sanitize(name: str, prefix: str) -> str:
    return prefix + "".join(c if c.isalnum() else "_" for c in name)


def lint(
    text: str,
    declared: Optional[set] = None,
    declared_prefixes: Optional[set] = None,
    prefix: str = "pilosa_tpu_",
    labels: Optional[Dict[str, Tuple[str, ...]]] = None,
) -> List[str]:
    errors: List[str] = []
    types: Dict[str, str] = {}
    first_sample_seen: set = set()
    histogram_families = set()
    # histogram family -> {series labels (sans le): [(le, count)]}
    buckets: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    counts: Dict[Tuple[str, str], float] = {}
    sums: set = set()
    # labeled-family contract: sanitized family -> required key set;
    # families seen -> the label-key sets their series carried (le is a
    # histogram mechanism, not a label — stripped before comparison)
    required_keys: Dict[str, frozenset] = {
        _sanitize(fam, prefix): frozenset(keys)
        for fam, keys in (labels or {}).items()
    }

    for ln, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append(f"line {ln}: malformed TYPE line: {line!r}")
                continue
            _, _, name, mtype = parts
            if mtype not in _VALID_TYPES:
                errors.append(f"line {ln}: unknown metric type {mtype!r}")
            if name in types:
                errors.append(
                    f"line {ln}: duplicate TYPE declaration for {name!r}"
                )
            if name in first_sample_seen:
                errors.append(
                    f"line {ln}: TYPE for {name!r} appears after its "
                    "first sample"
                )
            types[name] = mtype
            if mtype == "histogram":
                histogram_families.add(name)
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {ln}: unparseable sample line: {line!r}")
            continue
        name = m.group("name")
        lbls = m.group("labels")
        try:
            value = float(m.group("value"))
        except ValueError:
            errors.append(f"line {ln}: non-numeric value in: {line!r}")
            continue
        family = _family_of(name, histogram_families)
        first_sample_seen.add(family)
        if family not in types:
            errors.append(
                f"line {ln}: sample {name!r} has no preceding TYPE "
                "declaration"
            )
            continue
        if labels is not None:
            keys = frozenset(
                k for k in _LABEL_PAIR_RE.findall(lbls or "") if k != "le"
            )
            want = required_keys.get(family)
            if want is not None:
                if keys != want:
                    missing = sorted(want - keys)
                    extra = sorted(keys - want)
                    detail = "; ".join(
                        p
                        for p in (
                            f"missing {missing}" if missing else "",
                            f"undeclared {extra}" if extra else "",
                        )
                        if p
                    )
                    errors.append(
                        f"line {ln}: labeled family {family!r} series "
                        f"violates its STAT_LABELS key set "
                        f"{sorted(want)}: {detail}"
                    )
            elif keys:
                errors.append(
                    f"line {ln}: family {family!r} renders labels "
                    f"{sorted(keys)} but is not declared in STAT_LABELS "
                    "— unlisted families must render unlabeled"
                )
        if types[family] == "histogram":
            series = _strip_le(lbls)
            if name.endswith("_bucket"):
                le_m = _LE_RE.search(lbls or "")
                if le_m is None:
                    errors.append(
                        f"line {ln}: histogram bucket without le label"
                    )
                    continue
                raw_le = le_m.group("le")
                le = float("inf") if raw_le == "+Inf" else float(raw_le)
                buckets.setdefault((family, series), []).append((le, value))
            elif name.endswith("_count"):
                counts[(family, series)] = value
            elif name.endswith("_sum"):
                sums.add((family, series))
            else:
                errors.append(
                    f"line {ln}: bare sample {name!r} inside histogram "
                    f"family {family!r}"
                )

    for (family, series), entries in buckets.items():
        label = f"{family}{{{series}}}" if series else family
        les = [le for le, _ in entries]
        if les != sorted(les):
            errors.append(f"{label}: bucket le bounds not ascending")
        vals = [v for _, v in entries]
        if any(b < a for a, b in zip(vals, vals[1:])):
            errors.append(f"{label}: bucket counts not monotone (not cumulative)")
        if not les or les[-1] != float("inf"):
            errors.append(f"{label}: missing +Inf bucket")
        else:
            total = counts.get((family, series))
            if total is None:
                errors.append(f"{label}: histogram without _count series")
            elif vals[-1] != total:
                errors.append(
                    f"{label}: +Inf bucket {vals[-1]} != _count {total}"
                )
        if (family, series) not in sums:
            errors.append(f"{label}: histogram without _sum series")

    if declared is not None:
        allowed = {_sanitize(n, prefix) for n in declared}
        allowed_prefixes = tuple(
            _sanitize(p, prefix) for p in (declared_prefixes or ())
        )
        for family in types:
            if family in allowed or family.startswith(allowed_prefixes):
                continue
            errors.append(
                f"{family}: rendered but not declared in STAT_NAMES / "
                "STAT_PREFIXES"
            )
    return errors


def lint_against_registry(text: str) -> List[str]:
    """lint() against the package's own declared metric names AND its
    labeled-family contract (STAT_LABELS)."""
    from pilosa_tpu.utils.stats import STAT_LABELS, STAT_NAMES, STAT_PREFIXES

    return lint(
        text,
        declared=set(STAT_NAMES),
        declared_prefixes=set(STAT_PREFIXES),
        labels=dict(STAT_LABELS),
    )


def main(argv: List[str]) -> int:
    data = (
        open(argv[0], encoding="utf-8").read()
        if argv
        else sys.stdin.read()
    )
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    errors = lint_against_registry(data)
    for e in errors:
        print(f"prom-lint: {e}")
    if not errors:
        print("prom-lint: clean")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
