#!/usr/bin/env python
"""Repository analysis gate: AST passes, and optionally ruff + mypy.

Usage:
    python tools/check.py              # AST passes against the baseline
    python tools/check.py --all       # + ruff + mypy (skipped if absent)
    python tools/check.py --list      # show registered passes
    python tools/check.py --no-baseline   # raw findings, nothing allowed

Exit code 0 means every enabled checker is clean; any finding not
covered by tools/analysis_baseline.toml — or any stale baseline entry —
is a failure. tests/test_static_analysis.py runs the AST half of this
gate inside tier-1, so CI fails with the same file:line evidence this
prints.

ruff/mypy are optional: environments without them (the hermetic test
container) skip those steps with a notice rather than failing, so the
gate degrades to the AST passes instead of blocking. Their
configuration lives in pyproject.toml.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from pilosa_tpu import analysis  # noqa: E402


def run_ast_passes(baseline: bool) -> int:
    if baseline:
        baseline_path = os.path.join(
            REPO_ROOT, "tools", "analysis_baseline.toml"
        )
        result = analysis.check(REPO_ROOT, baseline_path=baseline_path)
    else:
        # raw mode: bypass analysis.check()'s baseline auto-discovery
        modules = analysis.load_modules(REPO_ROOT)
        result = analysis.run_gate(
            analysis.default_passes(), modules, baseline=None
        )
    if baseline and result.suppressed:
        print(
            f"analysis: {len(result.suppressed)} finding(s) covered by "
            "the committed baseline (tools/analysis_baseline.toml)"
        )
    print(result.render())
    return 0 if result.ok else 1


def _tool_available(module: str) -> bool:
    try:
        __import__(module)
        return True
    except ImportError:
        return False


def run_tool(name: str, args: List[str]) -> int:
    """Run an optional external checker; missing tools skip, not fail."""
    if not _tool_available(name):
        print(f"{name}: not installed here — skipped (config in pyproject.toml)")
        return 0
    proc = subprocess.run(
        [sys.executable, "-m", name, *args], cwd=REPO_ROOT
    )
    return proc.returncode


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--all",
        action="store_true",
        help="also run ruff and mypy (when installed)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report raw findings, ignoring tools/analysis_baseline.toml",
    )
    ap.add_argument(
        "--list", action="store_true", help="list registered AST passes"
    )
    args = ap.parse_args(argv)

    if args.list:
        for p in analysis.default_passes():
            print(p.name)
        return 0

    rc = 0
    if args.all:
        rc |= run_tool("ruff", ["check", "pilosa_tpu", "tools", "tests"])
        rc |= run_tool(
            "mypy",
            [
                "pilosa_tpu/analysis",
                "pilosa_tpu/utils/locks.py",
                "pilosa_tpu/utils/race.py",
                "pilosa_tpu/utils/resources.py",
                "pilosa_tpu/sched",
                "pilosa_tpu/core/wal.py",
                "pilosa_tpu/core/devcache.py",
                "pilosa_tpu/core/resultcache.py",
                "pilosa_tpu/hbm",
            ],
        )
    rc |= run_ast_passes(baseline=not args.no_baseline)
    if rc == 0:
        print("check: OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
