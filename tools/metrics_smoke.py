#!/usr/bin/env python
"""Tier-1-adjacent metrics smoke: boot a real 3-node cluster, drive
query and ingest traffic at TWO indexes over HTTP, then lint both the
per-node /metrics and the federated /cluster/metrics expositions
(tools/prom_lint.py — TYPE-once, histogram bucket monotonicity, every
rendered family declared in STAT_NAMES, labeled families honoring
STAT_LABELS). Also asserts the two indexes' per-index families are
present and disjoint in the cluster rollup, and that /cluster/health
answers. Exits non-zero on any finding.

Run by .github/workflows/ci.yml alongside tools/check.py; runnable
locally with `JAX_PLATFORMS=cpu python tools/metrics_smoke.py`.
"""

from __future__ import annotations

import json
import os
import re
import sys
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pilosa_tpu.utils.cpuonly import force_cpu  # noqa: E402

force_cpu(2)

from pilosa_tpu.testing import ClusterHarness  # noqa: E402
from tools.prom_lint import lint_against_registry  # noqa: E402


def _post(uri: str, path: str, payload: dict) -> dict:
    req = urllib.request.Request(
        f"{uri}{path}", data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def _get(uri: str, path: str):
    with urllib.request.urlopen(f"{uri}{path}", timeout=10) as r:
        return r.read().decode()


def _index_labels(text: str, family: str) -> set:
    """index label values rendered for one family in exposition text."""
    out = set()
    for m in re.finditer(
        rf'{family}(?:_bucket|_sum|_count)?\{{([^}}]*)\}}', text
    ):
        lm = re.search(r'index="([^"]*)"', m.group(1))
        if lm:
            out.add(lm.group(1))
    return out


def _tenant_overload(errors: list) -> None:
    """Two-tenant QoS scenario (ISSUE 16) on its own 1-node harness:
    one index rate-limited to 1 qps, one held to a 1000-byte HBM quota.
    The abuser's second query must shed 429 with the X-Pilosa-Quota-*
    headers; the hog's second distinct row must trip quota-first
    eviction; the tenant.* gauge families and the reason-tagged
    sched.shed series must render on a lint-clean /metrics page."""
    import urllib.error

    from pilosa_tpu.testing import ClusterHarness

    with ClusterHarness(
        1, in_memory=True, metric_poll_interval=0.0,
        telemetry_sample_interval=0.0,
        tenant_overrides=["smoke_abuser:qps=1", "smoke_hog:hbm-bytes=1000"],
    ) as cluster:
        srv = cluster[0]
        uri = srv.node.uri
        for idx in ("smoke_abuser", "smoke_hog"):
            srv.api.create_index(idx)
            srv.api.create_field(idx, "f", {"type": "set"})
            _post(
                uri, f"/index/{idx}/field/f/import",
                {"rows": [1] * 8 + [2] * 8,
                 "cols": list(range(8)) + list(range(8))},
            )
        # the abuser's burst token serves one query; the immediate
        # repeat must shed with the informed headers
        resp = _post(uri, "/index/smoke_abuser/query",
                     {"query": "Count(Row(f=1))"})
        assert resp["results"] == [8], resp
        try:
            _post(uri, "/index/smoke_abuser/query",
                  {"query": "Count(Row(f=1))"})
            errors.append("tenant smoke: second 1-qps query was not shed")
        except urllib.error.HTTPError as e:
            if e.code != 429:
                errors.append(f"tenant smoke: expected 429, got {e.code}")
            if e.headers.get("X-Pilosa-Quota-Limit") != "qps":
                errors.append(
                    "tenant smoke: 429 missing X-Pilosa-Quota-Limit=qps "
                    f"(got {dict(e.headers)})"
                )
            if not e.headers.get("Retry-After"):
                errors.append("tenant smoke: 429 missing Retry-After")
            e.close()
        # two distinct row operands cannot both fit a 1000-byte device
        # quota: the second insert must evict the first (quota-first,
        # global budget far from pressure)
        for row in (1, 2):
            resp = _post(uri, "/index/smoke_hog/query",
                         {"query": f"Count(Row(f={row}))"})
            assert resp["results"] == [8], resp
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        qev = DEVICE_CACHE.quota_evictions_by_index()
        if qev.get("smoke_hog", 0) <= 0:
            errors.append(
                f"tenant smoke: no quota evictions for smoke_hog: {qev}"
            )
        srv.publish_cache_gauges()
        text = _get(uri, "/metrics")
        overview = json.loads(_get(uri, "/cluster/overview"))
    for e in lint_against_registry(text):
        errors.append(f"tenant /metrics: {e}")
    if not re.search(
        r'^pilosa_tpu_sched_shed\{[^}]*index="smoke_abuser"[^}]*'
        r'reason="rate"[^}]*\} ',
        text, re.M,
    ) and not re.search(
        r'^pilosa_tpu_sched_shed\{[^}]*reason="rate"[^}]*'
        r'index="smoke_abuser"[^}]*\} ',
        text, re.M,
    ):
        errors.append(
            "tenant /metrics: sched.shed{index=smoke_abuser,reason=rate} "
            "missing"
        )
    for fam in (
        "pilosa_tpu_tenant_hbm_quota_bytes",
        "pilosa_tpu_tenant_quota_evictions",
    ):
        if not re.search(rf'^{fam}\{{', text, re.M):
            errors.append(f"tenant /metrics: {fam} missing")
    row = overview.get("indexes", {}).get("smoke_hog")
    if not row or row.get("quotaBytes") != 1000:
        errors.append(
            f"/cluster/overview: smoke_hog quotaBytes != 1000: {row}"
        )
    if row and row.get("quotaEvictions", 0) <= 0:
        errors.append(
            f"/cluster/overview: smoke_hog quotaEvictions stayed 0: {row}"
        )


def _tier_smoke(errors: list) -> None:
    """Tiered-storage scenario (ISSUE 18) on its own 1-node harness
    backed by an in-memory object store: import, demote over HTTP, run
    a COLD query (first read hydrates single-flight), then assert the
    tier.* counter families and the per-index cold/local gauges render
    on a lint-clean /metrics page with the values the protocol implies."""
    from pilosa_tpu.testing import ClusterHarness
    from pilosa_tpu.tier.store import MemoryStore

    with ClusterHarness(
        1, in_memory=True, metric_poll_interval=0.0,
        telemetry_sample_interval=0.0,
        tier_store=MemoryStore(), tier_placement="cold",
    ) as cluster:
        srv = cluster[0]
        uri = srv.node.uri
        srv.api.create_index("smoke_cold")
        srv.api.create_field("smoke_cold", "f", {"type": "set"})
        _post(
            uri, "/index/smoke_cold/field/f/import",
            {"rows": [1] * 16, "cols": list(range(16))},
        )
        resp = _post(uri, "/index/smoke_cold/query",
                     {"query": "Count(Row(f=1))"})
        assert resp["results"] == [16], resp
        r = _post(uri, "/internal/tier/demote"
                       "?index=smoke_cold&field=f&shard=0", {})
        if not (r.get("demoted") and r.get("cold")):
            errors.append(f"tier smoke: HTTP demote did not go cold: {r}")
        st = json.loads(_get(uri, "/internal/tier/status"))
        if len(st.get("coldFragments", [])) != 1:
            errors.append(f"tier smoke: status coldFragments != 1: {st}")
        # the COLD query: a shape the result cache has NOT seen (the
        # warm Count above is cache-served after demote precisely
        # because demotion changes no data), so its first read must
        # hydrate (exactly one fetch) and still answer exactly
        resp = _post(uri, "/index/smoke_cold/query",
                     {"query": "Row(f=1)"})
        assert resp["results"][0]["columns"] == list(range(16)), resp
        tc = srv.tier.counters()
        for name, want in (("demotions", 1), ("hydrations", 1),
                           ("fetches", 1)):
            if tc.get(name) != want:
                errors.append(
                    f"tier smoke: counter {name} = {tc.get(name)}, "
                    f"expected {want} after demote + one cold query"
                )
        srv.publish_cache_gauges()
        text = _get(uri, "/metrics")
    for e in lint_against_registry(text):
        errors.append(f"tier /metrics: {e}")
    for fam, want_min in (
        ("pilosa_tpu_tier_demotions", 1.0),
        ("pilosa_tpu_tier_demote_bytes", 1.0),
        ("pilosa_tpu_tier_hydrations", 1.0),
        ("pilosa_tpu_tier_fetches", 1.0),
        ("pilosa_tpu_tier_fetch_bytes", 1.0),
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", text, re.M)
        if m is None:
            errors.append(f"tier /metrics: {fam} missing")
        elif float(m.group(1)) < want_min:
            errors.append(
                f"tier /metrics: {fam} = {m.group(1)}, expected >= "
                f"{want_min}"
            )
    for fam in ("pilosa_tpu_tier_cold_fragments",
                "pilosa_tpu_tier_local_bytes"):
        if not re.search(rf'^{fam}\{{index="smoke_cold"\}} ', text, re.M):
            errors.append(
                f"tier /metrics: {fam}{{index=smoke_cold}} missing"
            )


def _coherence_smoke(errors: list) -> None:
    """Cache-coherence scenario (ISSUE 19) on its own 2-node leased
    harness, driven entirely over HTTP: a warm fan-out hit that pays
    ZERO version RTTs (counter delta asserted), one subscription
    receiving a pushed update after a write issued at the REMOTE node,
    and the coherence.* families rendering on a lint-clean /metrics
    page on both the holder and the publisher."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.testing import ClusterHarness

    with ClusterHarness(
        2, in_memory=True, metric_poll_interval=0.0,
        telemetry_sample_interval=0.0,
        coherence_lease_duration=30.0,
        coherence_publish_batch_ms=10.0,
        coherence_sub_poll_interval=0.2,
    ) as cluster:
        srv = cluster[0]
        uri = srv.node.uri
        srv.api.create_index("smoke_coh")
        srv.api.create_field("smoke_coh", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + k for s in range(4) for k in range(10)]
        _post(
            uri, "/index/smoke_coh/field/f/import",
            {"rows": [1] * len(cols), "cols": cols},
        )
        q = {"query": "Count(Row(f=1))"}
        for _ in range(2):  # cold + mirror-armed repeat
            resp = _post(uri, "/index/smoke_coh/query", q)
            assert resp["results"] == [len(cols)], resp
        mgr = srv.coherence
        rtts0 = mgr.counters_snapshot()["version_rtts"]
        hits0 = mgr.counters_snapshot()["lease_hits"]
        resp = _post(uri, "/index/smoke_coh/query", q)
        assert resp["results"] == [len(cols)], resp
        csnap = mgr.counters_snapshot()
        if csnap["version_rtts"] != rtts0:
            errors.append(
                "coherence smoke: leased warm hit paid "
                f"{csnap['version_rtts'] - rtts0} version RTT(s); "
                "expected 0"
            )
        if csnap["lease_hits"] <= hits0:
            errors.append(
                "coherence smoke: lease_hits did not move on a warm hit"
            )
        # subscription: registered over the wire, updated by a write
        # POSTed at the REMOTE node, delivered via the long-poll GET
        sub = _post(
            uri, "/subscriptions",
            {"index": "smoke_coh", "query": "Count(Row(f=5))"},
        )
        assert sub["seq"] == 1 and sub["result"] == [0], sub
        # the write must land on a REMOTE-owned shard so the update
        # travels the publish plane (publisher bump -> holder mirror ->
        # push), not a purely local invalidation
        remote_shard = next(
            s for s in range(4)
            if cluster[0].cluster.shard_nodes("smoke_coh", s)[0].id
            != srv.node.id
        )
        _post(
            cluster[1].node.uri, "/index/smoke_coh/field/f/import",
            {"rows": [5], "cols": [remote_shard * SHARD_WIDTH + 3]},
        )
        snap = json.loads(
            _get(uri, f"/subscriptions/{sub['id']}?after=1&wait=15")
        )
        if snap.get("seq", 1) < 2 or snap.get("result") != [1]:
            errors.append(
                f"coherence smoke: no pushed update after a remote "
                f"write: {snap}"
            )
        for s in cluster.nodes:
            s.publish_cache_gauges()
        holder_text = _get(uri, "/metrics")
        publisher_text = _get(cluster[1].node.uri, "/metrics")
    for label, text in (
        ("holder", holder_text), ("publisher", publisher_text),
    ):
        for e in lint_against_registry(text):
            errors.append(f"coherence {label} /metrics: {e}")
    for fam, want_min in (
        ("pilosa_tpu_coherence_lease_hits", 1.0),
        ("pilosa_tpu_coherence_leases", 1.0),
        ("pilosa_tpu_coherence_sub_pushes", 1.0),
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", holder_text, re.M)
        if m is None:
            errors.append(f"coherence holder /metrics: {fam} missing")
        elif float(m.group(1)) < want_min:
            errors.append(
                f"coherence holder /metrics: {fam} = {m.group(1)}, "
                f"expected >= {want_min}"
            )
    # the version-RTT counter renders (at 0: every hit was leased)
    if not re.search(
        r"^pilosa_tpu_coherence_version_rtts ", holder_text, re.M
    ):
        errors.append(
            "coherence holder /metrics: coherence.version_rtts missing"
        )
    if not re.search(
        r'^pilosa_tpu_coherence_subscriptions\{index="smoke_coh"\} 1',
        holder_text, re.M,
    ):
        errors.append(
            "coherence holder /metrics: "
            "coherence.subscriptions{index=smoke_coh} != 1"
        )
    for fam in (
        "pilosa_tpu_coherence_grants",
        "pilosa_tpu_coherence_grants_issued",
        "pilosa_tpu_coherence_publishes",
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", publisher_text, re.M)
        if m is None:
            errors.append(f"coherence publisher /metrics: {fam} missing")
        elif float(m.group(1)) < 1.0:
            errors.append(
                f"coherence publisher /metrics: {fam} = {m.group(1)}, "
                "expected >= 1"
            )


def main() -> int:
    errors: list = []
    with ClusterHarness(
        3, replica_n=1, in_memory=True, metric_poll_interval=0.0,
        telemetry_sample_interval=0.0, mesh_group="smoke-ici",
    ) as cluster:
        uri = cluster[0].node.uri
        for idx in ("smoke_a", "smoke_b"):
            cluster[0].api.create_index(idx)
            cluster[0].api.create_field(idx, "f", {"type": "set"})
        # traffic tagged to two indexes: ingest (import endpoint) plus
        # enough Counts to fill per-index query_ms histograms on
        # whichever nodes own the shards
        for idx, n_cols in (("smoke_a", 40), ("smoke_b", 12)):
            _post(
                uri, f"/index/{idx}/field/f/import",
                {"rows": [1] * n_cols, "cols": list(range(n_cols))},
            )
            for _ in range(3):
                resp = _post(
                    uri, f"/index/{idx}/query",
                    {"query": "Count(Row(f=1))"},
                )
                # exact: a routing regression that silently drops bits
                # must fail the smoke, not render a plausible page
                assert resp["results"] == [n_cols], resp
        # ISSUE 9: one staged import burst, then a query whose read
        # barrier merges it — the merge-barrier gauges must move and the
        # extent-patch counter must render (asserted below on the
        # scraped text)
        _post(
            uri, "/index/smoke_a/field/f/import",
            {"rows": [2] * 600, "cols": list(range(600))},
        )
        resp = _post(
            uri, "/index/smoke_a/query", {"query": "Count(Row(f=2))"}
        )
        assert resp["results"] == [600], resp
        # mesh-group execution (ISSUE 10): a Count spanning shards on at
        # least two owner nodes folds into ONE mesh dispatch (the whole
        # harness shares the "smoke-ici" domain) — this is what moves
        # the mesh.local_shards / mesh.collective_bytes gauges asserted
        # below
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        mesh_cols = [i * SHARD_WIDTH for i in range(6)]
        _post(
            uri, "/index/smoke_a/field/f/import",
            {"rows": [3] * len(mesh_cols), "cols": mesh_cols},
        )
        resp = _post(
            uri, "/index/smoke_a/query", {"query": "Count(Row(f=3))"}
        )
        assert resp["results"] == [len(mesh_cols)], resp
        # versioned result cache (ISSUE 14): re-issue an IDENTICAL Count
        # and assert the repeat served from the cache — cache.hits moved
        # and the second query issued ZERO compiled dispatches (the
        # in-process plan.STATS counter is the ground truth the gauges
        # summarize)
        from pilosa_tpu.exec import plan as planmod

        repeat_q = {"query": "Count(Row(f=2))"}
        resp = _post(uri, "/index/smoke_a/query", repeat_q)
        assert resp["results"] == [600], resp
        evals_before = planmod.STATS["evals"]
        from pilosa_tpu.core.resultcache import RESULT_CACHE

        hits_before = RESULT_CACHE.stats_snapshot()["hits"]
        resp = _post(uri, "/index/smoke_a/query", repeat_q)
        assert resp["results"] == [600], resp
        if planmod.STATS["evals"] != evals_before:
            errors.append(
                "repeat Count dispatched "
                f"{planmod.STATS['evals'] - evals_before} compiled "
                "program(s); expected a zero-dispatch cache hit"
            )
        if RESULT_CACHE.stats_snapshot()["hits"] <= hits_before:
            errors.append("cache.hits did not move on a repeat Count")
        # timeline sampler exposes the cache's footprint + hit rate
        tl = json.loads(_get(uri, "/debug/timeline?sample=1"))
        samples = tl.get("samples") or []
        if not samples or "cacheHitRate" not in samples[-1]:
            errors.append("timeline sample missing cacheHitRate")
        elif samples[-1]["cacheHitRate"] <= 0:
            errors.append(
                f"timeline cacheHitRate = {samples[-1]['cacheHitRate']}, "
                "expected > 0 after a cache-served repeat"
            )
        # plane-streamed BSI aggregates (ISSUE 15): drive one Range
        # count through the streamed lowering and assert it matches a
        # host recompute of the imported values — the bsi.* gauge
        # families asserted on the scraped text below must have moved
        _post(
            uri, "/index/smoke_a/field/val",
            {"options": {"type": "int", "min": 0, "max": 1000}},
        )
        bsi_vals = [(i, (i * 37) % 1000) for i in range(200)]
        _post(
            uri, "/index/smoke_a/field/val/import-value",
            {"cols": [c for c, _ in bsi_vals],
             "values": [v for _, v in bsi_vals]},
        )
        want_range = sum(1 for _, v in bsi_vals if v > 500)
        resp = _post(
            uri, "/index/smoke_a/query", {"query": "Count(Row(val > 500))"}
        )
        assert resp["results"] == [want_range], (resp, want_range)
        from pilosa_tpu.exec import bsistream

        bsnap = bsistream.stats_snapshot()
        if bsnap["plane_dispatches"] <= 0 or bsnap["slabs"] <= 0:
            errors.append(
                f"streamed BSI range issued no slab dispatches: {bsnap}"
            )
        # the resize-job record must scrape as well-formed JSON on a live
        # node (operators poll it during elastic resizes; an idle node
        # reports NONE)
        job = json.loads(_get(uri, "/cluster/resize/job"))
        assert job.get("state") == "NONE", f"unexpected resize job: {job}"

        node_texts = [_get(s.node.uri, "/metrics") for s in cluster.nodes]
        node_text = node_texts[0]
        cluster_text = _get(uri, "/cluster/metrics")
        overview = json.loads(_get(uri, "/cluster/overview"))
        health = json.loads(_get(uri, "/cluster/health"))

    for label, text in (("node", node_text), ("cluster", cluster_text)):
        for e in lint_against_registry(text):
            errors.append(f"{label} /metrics: {e}")

    # the smoke must actually have produced the histogram the dashboards
    # and the admission tail estimate depend on
    if "pilosa_tpu_query_ms_bucket" not in cluster_text:
        errors.append("query_ms histogram missing from /cluster/metrics")

    # deferred-delta merge plane (ISSUE 9): the staged burst above was
    # merged by the query's read barrier, so the merge gauges and the
    # extent-patch counter must render — and merge_batches must have
    # actually moved (a burst that silently bypassed the staged path
    # would leave it zero)
    for fam in (
        "pilosa_tpu_ingest_merge_ms",
        "pilosa_tpu_ingest_merge_batches",
        "pilosa_tpu_ingest_merge_device",
        "pilosa_tpu_hbm_extent_patches",
        "pilosa_tpu_hbm_extent_patch_batches",
    ):
        if not re.search(rf"^{fam} ", node_text, re.M):
            errors.append(f"node /metrics: {fam} missing")

    # plane-streamed BSI aggregates (ISSUE 15): the bsi.* families must
    # render and the slab/dispatch counters must have moved for the
    # Range query driven above
    for fam in (
        "pilosa_tpu_bsi_slabs",
        "pilosa_tpu_bsi_slab_bytes",
        "pilosa_tpu_bsi_plane_dispatches",
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", node_text, re.M)
        if m is None:
            errors.append(f"node /metrics: {fam} missing")
        elif float(m.group(1)) <= 0:
            errors.append(
                f"node /metrics: {fam} = {m.group(1)}, expected > 0 after "
                "a streamed BSI Range query"
            )
    m = re.search(
        r"^pilosa_tpu_ingest_merge_batches ([0-9.e+-]+)", node_text, re.M
    )
    if m and float(m.group(1)) <= 0:
        errors.append("ingest.merge_batches stayed zero after a staged burst")

    # versioned result cache (ISSUE 14): the gauge families must render
    # and the hit counter must reflect the cache-served repeat above
    for fam, want_min in (
        ("pilosa_tpu_cache_hits", 1.0),
        ("pilosa_tpu_cache_misses", 1.0),
        ("pilosa_tpu_cache_revalidations", 1.0),
        ("pilosa_tpu_cache_entries", 1.0),
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", node_text, re.M)
        if m is None:
            errors.append(f"node /metrics: {fam} missing")
        elif float(m.group(1)) < want_min:
            errors.append(
                f"node /metrics: {fam} = {m.group(1)}, expected >= {want_min}"
            )
    if not re.search(
        r'^pilosa_tpu_cache_resident_bytes\{index="smoke_a"\} ',
        node_text, re.M,
    ):
        errors.append(
            "node /metrics: cache.resident_bytes{index=smoke_a} missing"
        )

    # mesh-group execution (ISSUE 10): the cluster runs as one ICI
    # domain, so the Counts above must have ridden mesh dispatches —
    # all three mesh gauges must render and group_size must equal the
    # 3 registered members (local_shards moving proves at least one
    # fan-out actually folded instead of paying HTTP legs)
    for fam, want_min in (
        ("pilosa_tpu_mesh_group_size", 3.0),
        ("pilosa_tpu_mesh_local_shards", 1.0),
        ("pilosa_tpu_mesh_collective_bytes", 1.0),
    ):
        m = re.search(rf"^{fam} ([0-9.e+-]+)", node_text, re.M)
        if m is None:
            errors.append(f"node /metrics: {fam} missing")
        elif float(m.group(1)) < want_min:
            errors.append(
                f"node /metrics: {fam} = {m.group(1)}, expected >= {want_min}"
            )

    # per-index attribution: both tenants present, and their label sets
    # disjoint from each other (a merge that smeared series across
    # indexes would collapse them)
    for family in ("pilosa_tpu_query_ms", "pilosa_tpu_ingest_bits"):
        got = _index_labels(cluster_text, family)
        for idx in ("smoke_a", "smoke_b"):
            if idx not in got:
                errors.append(
                    f"/cluster/metrics: {family} missing index={idx!r} "
                    f"series (got {sorted(got)})"
                )
    # merge exactness: the cluster rollup's per-index ingest.bits must
    # equal the SUM of the three per-node values exactly (counters are
    # extensive quantities; smearing across indexes or peers would
    # break equality on at least one tenant, since they wrote 40 vs 12
    # bits)
    def _bits(text: str, idx: str) -> float:
        m = re.search(
            rf'pilosa_tpu_ingest_bits\{{index="{idx}"\}} ([0-9.e+-]+)',
            text,
        )
        return float(m.group(1)) if m else 0.0

    for idx in ("smoke_a", "smoke_b"):
        want = sum(_bits(t, idx) for t in node_texts)
        got = _bits(cluster_text, idx)
        if want <= 0 or got != want:
            errors.append(
                f"/cluster/metrics: ingest.bits for {idx}: cluster "
                f"{got} != sum of node values {want}"
            )

    # the overview and health endpoints must answer with their headline
    # fields on a healthy cluster
    if len(overview.get("nodes", [])) != 3:
        errors.append(f"/cluster/overview: expected 3 nodes: {overview}")
    if any(n["stale"] for n in overview.get("nodes", [])):
        errors.append(f"/cluster/overview: live peers marked stale: {overview}")
    if health.get("status") != "ok":
        errors.append(f"/cluster/health: expected ok: {health}")

    # the main harness runs with NO tenant limits configured: the
    # tenant.* gauge families must not render at all (opt-in series)
    if re.search(r"^pilosa_tpu_tenant_", node_text, re.M):
        errors.append(
            "node /metrics: tenant.* series rendered without any "
            "tenant limits configured"
        )

    # the main harness is also UNTIERED: the tier.* families are
    # opt-in and must not render at all
    if re.search(r"^pilosa_tpu_tier_", node_text, re.M):
        errors.append(
            "node /metrics: tier.* series rendered without tiered "
            "storage enabled"
        )

    # the main harness never leased, never subscribed: the coherence.*
    # families are opt-in and must not render at all
    if re.search(r"^pilosa_tpu_coherence_", node_text, re.M):
        errors.append(
            "node /metrics: coherence.* series rendered without any "
            "lease or subscription activity"
        )

    # multi-tenant QoS enforcement (ISSUE 16), on its own harness
    _tenant_overload(errors)

    # tiered storage (ISSUE 18), on its own harness
    _tier_smoke(errors)

    # cache coherence (ISSUE 19), on its own 2-node leased harness
    _coherence_smoke(errors)

    for e in errors:
        print(f"metrics-smoke: {e}")
    if not errors:
        n = sum(
            1
            for t in (node_text, cluster_text)
            for ln in t.splitlines()
            if ln and not ln.startswith("#")
        )
        print(f"metrics-smoke: OK ({n} samples linted)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
