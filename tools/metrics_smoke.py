#!/usr/bin/env python
"""Tier-1-adjacent metrics smoke: boot a real node, drive traffic over
HTTP, scrape /metrics, and lint the Prometheus exposition
(tools/prom_lint.py — TYPE-once, histogram bucket monotonicity, every
rendered family declared in STAT_NAMES). Exits non-zero on any finding.

Run by .github/workflows/ci.yml alongside tools/check.py; runnable
locally with `JAX_PLATFORMS=cpu python tools/metrics_smoke.py`.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pilosa_tpu.utils.cpuonly import force_cpu  # noqa: E402

force_cpu(2)

from pilosa_tpu.server.node import NodeServer  # noqa: E402
from tools.prom_lint import lint_against_registry  # noqa: E402


def main() -> int:
    srv = NodeServer(None, "smoke0", metric_poll_interval=0.0).start()
    try:
        uri = srv.node.uri
        srv.api.create_index("smoke")
        srv.api.create_field("smoke", "f", {"type": "set"})
        # traffic that exercises counters, gauges, and the query_ms /
        # ingest timing histograms — over real HTTP, like production
        body = json.dumps({"query": "Set(1, f=1) Set(2, f=1)"}).encode()
        req = urllib.request.Request(
            f"{uri}/index/smoke/query", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=30).read()
        for _ in range(3):
            req = urllib.request.Request(
                f"{uri}/index/smoke/query",
                data=json.dumps({"query": "Count(Row(f=1))"}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert resp["results"] == [2], resp
        # the resize-job record must scrape as well-formed JSON on a live
        # node (operators poll it during elastic resizes; an idle node
        # reports NONE)
        with urllib.request.urlopen(f"{uri}/cluster/resize/job", timeout=10) as r:
            job = json.loads(r.read())
        assert job.get("state") == "NONE", f"unexpected resize job: {job}"
        with urllib.request.urlopen(f"{uri}/metrics", timeout=10) as r:
            text = r.read().decode()
    finally:
        srv.stop()
    errors = lint_against_registry(text)
    for e in errors:
        print(f"metrics-smoke: {e}")
    # the smoke must actually have produced the histogram the dashboards
    # and the admission tail estimate depend on
    if "pilosa_tpu_query_ms_bucket" not in text:
        errors.append("query_ms histogram missing from /metrics")
        print("metrics-smoke: query_ms histogram missing from /metrics")
    if not errors:
        print(
            "metrics-smoke: OK "
            f"({sum(1 for ln in text.splitlines() if ln and not ln.startswith('#'))} samples linted)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
