"""Shard geometry constants.

Reference: /root/reference/shardwidth/20.go:19 (`Exponent = 20`) and
fragment.go:50-63. The shard width is the number of columns per shard; the
reference selects the exponent 16..32 via build tags. Here it is selected via
the PILOSA_TPU_SHARD_WIDTH_EXPONENT environment variable (read once at import,
mirroring the compile-time nature of the Go build tag).

Device geometry: bitmap rows are stored as dense little-endian uint32 words,
`WORDS_PER_ROW = SHARD_WIDTH / 32` per (row, shard). TPU VPU lanes are 32-bit;
uint32 (not uint64) keeps popcount and bitwise ops native-width on TPU.
"""

import os

# The reference allows exponents 16..32 (shardwidth build tags). We cap at 30:
# device arithmetic traces range bounds as int32 (x64 stays off for TPU), so
# in-shard positions must stay below 2^31 — and a 2^30-column shard already
# exceeds any practical fragment (128 MiB dense per row). Exponent 31/32 would
# also let a single row's popcount wrap uint32.
SHARD_WIDTH_EXPONENT = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH_EXPONENT", "20"))
if not 16 <= SHARD_WIDTH_EXPONENT <= 30:
    raise ValueError(
        f"PILOSA_TPU_SHARD_WIDTH_EXPONENT must be in [16, 30], got {SHARD_WIDTH_EXPONENT}"
    )

SHARD_WIDTH = 1 << SHARD_WIDTH_EXPONENT

# Number of 32-bit words that hold one row's bits within one shard.
WORDS_PER_ROW = SHARD_WIDTH // 32

# A container spans 2^16 bits (reference: roaring 2^16-wide containers,
# fragment.go:55-63 shardVsContainerExponent). Retained for the roaring
# interchange codec and block-checksum geometry.
CONTAINER_WIDTH = 1 << 16
CONTAINERS_PER_SHARD = SHARD_WIDTH // CONTAINER_WIDTH

# Anti-entropy block geometry (reference: fragment.go:81 HashBlockSize = 100).
HASH_BLOCK_SIZE = 100


def shard_of(col: int) -> int:
    """Shard that owns an absolute column id."""
    return col >> SHARD_WIDTH_EXPONENT


def pos_in_shard(col: int) -> int:
    """Column position within its shard."""
    return col & (SHARD_WIDTH - 1)


def pos(row_id: int, col_id: int) -> int:
    """Fragment-local bit position (reference: fragment.go:3090
    `pos = rowID*ShardWidth + columnID%ShardWidth`)."""
    return row_id * SHARD_WIDTH + (col_id & (SHARD_WIDTH - 1))
