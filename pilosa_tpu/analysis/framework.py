"""Static-analysis pass framework: modules, findings, baseline, runner.

The analysis subsystem is a small, dependency-free AST lint engine that
encodes this repo's concurrency and JAX-purity invariants (see the pass
modules: lock_hygiene, jax_purity, api_invariants). It is wired into
tier-1 via tests/test_static_analysis.py and into CI/dev loops via
tools/check.py.

Design points:

* A `Pass` runs over the whole module set at once (cross-module passes
  like the stats-registry check need the global view).
* Findings are suppressed only through the committed baseline file
  (tools/analysis_baseline.toml), where every entry carries a mandatory
  human-written `reason`. A baseline entry that matches nothing is itself
  an error — the baseline can only shrink or be re-justified, never rot.
* Baseline entries match on (code, path, message-substring), NOT line
  numbers, so unrelated edits don't invalidate them.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

try:  # tomllib is stdlib only from 3.11; 3.10 environments carry tomli
    import tomllib
except ImportError:  # pragma: no cover - depends on interpreter version
    import tomli as tomllib  # type: ignore[no-redef]

__all__ = [
    "Module",
    "Finding",
    "Pass",
    "Baseline",
    "BaselineEntry",
    "GateResult",
    "load_modules",
    "load_source_module",
    "run_passes",
    "run_gate",
    "validate_baseline",
]


@dataclass(frozen=True)
class Module:
    """One parsed source file."""

    path: str  # absolute
    rel: str  # repo-root-relative, posix separators
    source: str
    tree: ast.Module


@dataclass(frozen=True)
class Finding:
    """One rule violation at a specific site."""

    code: str  # e.g. "LOCK002"
    path: str  # repo-root-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class Pass:
    """Base class for analysis passes. Subclasses set `name`, declare
    the rule codes they can emit in `rules`, and implement run() over
    the full module set. The `rules` declaration is load-bearing:
    baseline entries name their pass (`rule = "<pass name>"`) and the
    gate rejects an entry whose pass or code no longer exists — a
    renamed/removed rule must take its suppressions with it instead of
    leaving them to silently shadow an unrelated future rule that
    reuses the code."""

    name = "unnamed"
    rules: Tuple[str, ...] = ()

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        raise NotImplementedError


@dataclass(frozen=True)
class BaselineEntry:
    code: str
    path: str
    match: str  # substring of the finding message; "" matches any
    reason: str
    rule: str = ""  # owning pass name; validated against Pass.rules

    def covers(self, f: Finding) -> bool:
        return (
            f.code == self.code
            and f.path == self.path
            and (not self.match or self.match in f.message)
        )


@dataclass
class Baseline:
    entries: List[BaselineEntry] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "rb") as fh:
            data = tomllib.load(fh)
        entries: List[BaselineEntry] = []
        for i, raw in enumerate(data.get("allow", [])):
            for req in ("code", "path", "reason", "rule"):
                if not raw.get(req):
                    raise ValueError(
                        f"{path}: allow[{i}] is missing required key "
                        f"{req!r} — every baseline entry must be "
                        "justified and name the pass that owns its rule"
                    )
            entries.append(
                BaselineEntry(
                    code=str(raw["code"]),
                    path=str(raw["path"]),
                    match=str(raw.get("match", "")),
                    reason=str(raw["reason"]),
                    rule=str(raw["rule"]),
                )
            )
        return cls(entries)


@dataclass
class GateResult:
    """Outcome of one gate run: what fired, what the baseline ate,
    which baseline entries matched nothing (stale), and which name a
    pass/rule that no longer exists (invalid)."""

    findings: List[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: List[Finding] = field(default_factory=list)
    stale_entries: List[BaselineEntry] = field(default_factory=list)
    invalid_entries: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (
            not self.findings
            and not self.stale_entries
            and not self.invalid_entries
        )

    def render(self) -> str:
        out: List[str] = []
        for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.code)
        ):
            out.append(f.render())
        for e in self.stale_entries:
            out.append(
                f"{e.path}: STALE baseline entry {e.code} "
                f"(match={e.match!r}) no longer matches any finding — "
                "delete it"
            )
        out.extend(self.invalid_entries)
        if not out:
            out.append("analysis: clean")
        return "\n".join(out)


def load_source_module(path: str, rel: Optional[str] = None) -> Module:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return Module(
        path=os.path.abspath(path),
        rel=(rel if rel is not None else os.path.basename(path)),
        source=source,
        tree=ast.parse(source, filename=path),
    )


def load_modules(root: str, package_dir: str = "pilosa_tpu") -> List[Module]:
    """Parse every .py under root/package_dir (repo tree order)."""
    modules: List[Module] = []
    base = os.path.join(root, package_dir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames.sort()
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            modules.append(load_source_module(full, rel))
    return modules


def run_passes(
    passes: Sequence[Pass], modules: Sequence[Module]
) -> List[Finding]:
    findings: List[Finding] = []
    for p in passes:
        findings.extend(p.run(modules))
    return findings


def validate_baseline(
    passes: Sequence[Pass], baseline: Baseline
) -> List[str]:
    """Reject entries naming a pass or rule code that no longer exists.
    Without this, renaming LOCKNNN (or retiring a pass) leaves its
    suppressions behind to silently cover whatever future rule reuses
    the code — the baseline must shrink with the rule set."""
    by_name = {p.name: p for p in passes}
    problems: List[str] = []
    for e in baseline.entries:
        p = by_name.get(e.rule)
        if p is None:
            problems.append(
                f"{e.path}: INVALID baseline entry {e.code}: rule pass "
                f"{e.rule!r} is not registered "
                f"(known: {', '.join(sorted(by_name))}) — the pass was "
                "renamed or removed; update or delete the entry"
            )
        elif e.code not in p.rules:
            problems.append(
                f"{e.path}: INVALID baseline entry {e.code}: pass "
                f"{e.rule!r} declares no such rule "
                f"(its rules: {', '.join(p.rules) or 'none'}) — the rule "
                "was renamed or removed; update or delete the entry"
            )
    return problems


def run_gate(
    passes: Sequence[Pass],
    modules: Sequence[Module],
    baseline: Optional[Baseline] = None,
) -> GateResult:
    """Run passes, partition findings against the baseline, and report
    stale or invalid baseline entries."""
    all_findings = run_passes(passes, modules)
    if baseline is None:
        return GateResult(findings=all_findings)
    invalid = validate_baseline(passes, baseline)
    used: Dict[int, bool] = {i: False for i in range(len(baseline.entries))}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in all_findings:
        hit = False
        for i, e in enumerate(baseline.entries):
            if e.covers(f):
                used[i] = True
                hit = True
        (suppressed if hit else kept).append(f)
    stale = [e for i, e in enumerate(baseline.entries) if not used[i]]
    return GateResult(
        findings=kept,
        suppressed=suppressed,
        stale_entries=stale,
        invalid_entries=invalid,
    )


# -- shared AST helpers used by the concrete passes -------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local alias -> imported dotted origin for a module.

    `import numpy as np` -> {"np": "numpy"};
    `from jax import jit` -> {"jit": "jax.jit"};
    `import jax.numpy as jnp` -> {"jnp": "jax.numpy"}.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolve_call(node: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """Fully-qualified dotted origin of a call target, alias-resolved.

    With {"np": "numpy"}, `np.asarray(x)` -> "numpy.asarray";
    with {"urlopen": "urllib.request.urlopen"}, `urlopen(u)` ->
    "urllib.request.urlopen". Returns None for non-name targets
    (method calls on expressions, lambdas, subscripts, ...).
    """
    name = dotted_name(node.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    return f"{origin}.{rest}" if rest else origin
