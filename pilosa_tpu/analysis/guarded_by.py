"""Guarded-by inference: which `self._x` attributes does each lock protect?

Per class, the pass discovers the tracked locks (`self._mu = TrackedLock(...)`
/ `TrackedRLock` / `TrackedCondition` constructions, plus any `self.<name>`
used as `with self.<name>:` whose terminal matches the repo's lock naming
convention) and then classifies every `self._attr` access site by the set
of class locks lexically held around it. From that it infers, per private
attribute, the lock that CONSISTENTLY guards its writes — and flags mixed
access:

* **LOCK004** — an attribute written both under and outside its guard.
  Inference claims a guard only when at least `MIN_GUARDED_WRITES` write
  sites hold the same lock and guarded writes are not outnumbered by
  unguarded ones; a `# guarded-by:` annotation claims it unconditionally.
* **LOCK005** — a read of a guarded attribute with NO lock held, in a
  method that elsewhere takes that very lock: the author demonstrably
  knows the lock matters here, so the bare read is either a bug or an
  intentional racy snapshot that must say so.

What inference cannot see, annotations declare (trailing comments, read
from the source text):

    self._rows = {}            # guarded-by: _mu
    self.version = 0           # lock-free: monotonic int; GIL-atomic reads
    def _evict(self):          # guarded-by: _mu   (callback: caller holds it)

* `# guarded-by: <lock>` on an attribute's assignment pins its guard (the
  pass then enforces, never re-infers). On a `def` line it declares the
  whole method runs with the lock held (callbacks, `*_locked` helpers in
  classes with several locks).
* `# lock-free: <reason>` exempts the attribute entirely — init-before-
  publish handoffs, GIL-atomic counters read by gauge snapshots, versions
  validated elsewhere. The reason is mandatory (an empty one is itself a
  finding).

Conventions honored without annotation:

* `__init__` (and `__new__`) accesses are exempt: the constructor runs
  before the object is published.
* methods named `*_locked` are treated as holding the class's PRIMARY
  lock (the lock most often used in `with self.<lock>:` across the
  class) — the repo-wide convention for "caller holds the mutex".
* a `TrackedCondition(self._mu, ...)` attribute is an ALIAS of its lock:
  `with self._cv:` acquires `_mu`.
* nested functions/lambdas are skipped (they run later, like the
  lock-hygiene closure rule).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
)
from pilosa_tpu.analysis.lock_hygiene import LOCKISH_RE

__all__ = ["GuardedByPass", "MIN_GUARDED_WRITES"]

# inference claims a guard only from this many agreeing write sites —
# single-assignment attributes carry too little signal to accuse anyone
MIN_GUARDED_WRITES = 2

_ANNOT_RE = re.compile(
    r"#\s*(?P<kind>guarded-by|lock-free)\s*:\s*(?P<arg>[^#\n]*)"
)

_LOCK_CTORS = {"TrackedLock", "TrackedRLock", "TrackedCondition"}


@dataclass
class _ClassInfo:
    name: str
    lineno: int
    locks: Set[str] = field(default_factory=set)
    # condition attr -> underlying lock attr (TrackedCondition(self._mu))
    aliases: Dict[str, str] = field(default_factory=dict)
    # attr -> ("guarded-by", lock) | ("lock-free", reason)
    annotations: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    method_names: Set[str] = field(default_factory=set)
    class_attrs: Set[str] = field(default_factory=set)
    # method name -> declared held lock (def-line guarded-by annotation)
    method_guards: Dict[str, str] = field(default_factory=dict)
    # methods exempted wholesale (def-line `# lock-free: <reason>` —
    # init-before-publish phases like open()/replay)
    exempt_methods: Set[str] = field(default_factory=set)
    # attr -> list of (line, frozenset(held locks), method, is_write)
    accesses: Dict[str, List[Tuple[int, FrozenSet[str], str, bool]]] = field(
        default_factory=dict
    )
    with_counts: Dict[str, int] = field(default_factory=dict)
    # method name -> class locks it takes via `with` (LOCK005's "a
    # method that elsewhere takes the lock")
    method_with_locks: Dict[str, Set[str]] = field(default_factory=dict)
    bad_annotations: List[Tuple[int, str]] = field(default_factory=list)


def _line_annotations(source: str) -> Dict[int, Tuple[str, str]]:
    """lineno -> (kind, argument) for every guarded-by / lock-free
    trailing comment in the file."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _ANNOT_RE.search(line)
        if m:
            out[i] = (m.group("kind"), m.group("arg").strip())
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x` (or `cls.x`), else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _canon_lock(name: str) -> str:
    """First token of the annotation argument: `# guarded-by: _mu (why)`
    names lock `_mu`; the parenthetical is commentary for the reader."""
    return name.split()[0] if name.split() else ""


class _MethodScanner(ast.NodeVisitor):
    """Walk one method body, tracking which class locks are lexically
    held, recording every `self._attr` access site."""

    def __init__(
        self, info: _ClassInfo, method: str, base_held: FrozenSet[str]
    ):
        self.info = info
        self.method = method
        self.held: Set[str] = set(base_held)

    # deferred bodies: the lock context at the def site is meaningless
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is None:
            # class-level lock used via the class name
            # (e.g. `with WalWriter._lru_mu:`): take the terminal
            name = dotted_name(expr)
            if name is None:
                return None
            attr = name.rsplit(".", 1)[-1]
        if attr in self.info.locks or attr in self.info.aliases:
            return self.info.aliases.get(attr, attr)
        return None

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            lock = self._resolve_lock(item.context_expr)
            if lock is not None:
                entered.append(lock)
                self.info.with_counts[lock] = (
                    self.info.with_counts.get(lock, 0) + 1
                )
                self.info.method_with_locks.setdefault(
                    self.method, set()
                ).add(lock)
        newly = [lk for lk in entered if lk not in self.held]
        self.held.update(newly)
        for stmt in node.body:
            self.visit(stmt)
        for lk in newly:
            self.held.discard(lk)

    def _record(self, attr: str, lineno: int, is_write: bool) -> None:
        info = self.info
        if not attr.startswith("_") or attr.startswith("__"):
            return
        if attr in info.locks or attr in info.aliases:
            return
        if attr in info.method_names or attr in info.class_attrs:
            return
        info.accesses.setdefault(attr, []).append(
            (lineno, frozenset(self.held), self.method, is_write)
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(
                attr, node.lineno, isinstance(node.ctx, (ast.Store, ast.Del))
            )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # `self._pins[key] = n` / `del self._cache[k]`: a store through a
        # subscript MUTATES the container the attribute references —
        # that is a write for guarding purposes even though the
        # attribute itself is only Loaded
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._record(attr, node.lineno, True)
                self.visit(node.slice)
                return
        self.generic_visit(node)


class GuardedByPass(Pass):
    name = "guarded-by"
    rules = ("LOCK004", "LOCK005")

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        for m in modules:
            annots = _line_annotations(m.source)
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ClassDef):
                    info = self._collect(node, annots)
                    if info.locks:
                        self._report(m, info, findings)
        return findings

    # -- collection --------------------------------------------------------

    def _collect(
        self, cls: ast.ClassDef, annots: Dict[int, Tuple[str, str]]
    ) -> _ClassInfo:
        info = _ClassInfo(name=cls.name, lineno=cls.lineno)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.method_names.add(stmt.name)
                ann = annots.get(stmt.lineno)
                if ann and ann[0] == "guarded-by" and ann[1]:
                    info.method_guards[stmt.name] = _canon_lock(ann[1])
                elif ann and ann[0] == "lock-free":
                    if not ann[1]:
                        info.bad_annotations.append(
                            (
                                stmt.lineno,
                                f"`# lock-free:` on {cls.name}."
                                f"{stmt.name}() has no reason — say WHY "
                                "this method may touch guarded state "
                                "without the lock",
                            )
                        )
                    else:
                        info.exempt_methods.add(stmt.name)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name):
                        info.class_attrs.add(t.id)
        # lock attrs + attribute annotations from every method body
        for fn in [
            s
            for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    ctor = dotted_name(node.value.func)
                    ctor = ctor.rsplit(".", 1)[-1] if ctor else None
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        if ctor in _LOCK_CTORS:
                            info.locks.add(attr)
                            if ctor == "TrackedCondition" and node.value.args:
                                under = _self_attr(node.value.args[0])
                                if under is not None:
                                    info.aliases[attr] = under
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    ann = annots.get(node.lineno)
                    if ann:
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for t in targets:
                            attr = _self_attr(t)
                            if attr is None:
                                continue
                            kind, arg = ann
                            if kind == "lock-free" and not arg:
                                info.bad_annotations.append(
                                    (
                                        node.lineno,
                                        f"`# lock-free:` on {cls.name}."
                                        f"{attr} has no reason — say WHY "
                                        "the lock-free access is safe",
                                    )
                                )
                                continue
                            info.annotations[attr] = (kind, _canon_lock(arg))
        # class-body lock attrs (e.g. WalWriter._lru_mu at class level)
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ):
                ctor = dotted_name(stmt.value.func)
                ctor = ctor.rsplit(".", 1)[-1] if ctor else None
                if ctor in _LOCK_CTORS:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            info.locks.add(t.id)
                            info.class_attrs.discard(t.id)
        # conventionally-named `with self.<x>:` targets count as locks
        # even without a visible ctor (e.g. assigned via a factory)
        for fn in [
            s
            for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if (
                            attr is not None
                            and LOCKISH_RE.search(attr)
                            and attr not in info.aliases
                        ):
                            info.locks.add(attr)
        # alias targets that are not otherwise locks still resolve
        info.locks.update(info.aliases)
        # scan method bodies
        primary = self._primary_lock(cls, info)
        for fn in [
            s
            for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            if fn.name in ("__init__", "__new__"):
                continue
            if fn.name in info.exempt_methods:
                continue
            base: Set[str] = set()
            declared = info.method_guards.get(fn.name)
            if declared is not None:
                base.add(info.aliases.get(declared, declared))
            elif fn.name.endswith("_locked") and primary is not None:
                base.add(primary)
            scanner = _MethodScanner(info, fn.name, frozenset(base))
            for stmt in fn.body:
                scanner.visit(stmt)
        return info

    def _primary_lock(self, cls: ast.ClassDef, info: _ClassInfo) -> Optional[str]:
        counts: Dict[str, int] = {}
        for fn in [
            s
            for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr is None:
                            continue
                        lock = info.aliases.get(attr, attr)
                        if lock in info.locks or attr in info.locks:
                            counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            return None
        return max(counts.items(), key=lambda kv: kv[1])[0]

    # -- reporting ---------------------------------------------------------

    def _report(
        self, m: Module, info: _ClassInfo, findings: List[Finding]
    ) -> None:
        for lineno, msg in info.bad_annotations:
            findings.append(
                Finding(code="LOCK004", path=m.rel, line=lineno, message=msg)
            )
        for attr, sites in sorted(info.accesses.items()):
            ann = info.annotations.get(attr)
            if ann is not None and ann[0] == "lock-free":
                continue
            declared: Optional[str] = None
            if ann is not None and ann[0] == "guarded-by":
                declared = info.aliases.get(ann[1], ann[1])
            guard = declared or self._infer(sites)
            if guard is None:
                continue
            writes = [s for s in sites if s[3]]
            unguarded_writes = [s for s in writes if guard not in s[1]]
            for lineno, _held, method, _w in unguarded_writes:
                findings.append(
                    Finding(
                        code="LOCK004",
                        path=m.rel,
                        line=lineno,
                        message=(
                            f"{info.name}.{attr} written without "
                            f"{guard!r} in {method}() but its other "
                            "writes hold it — guard the write, or "
                            "annotate the attribute `# lock-free: "
                            "<reason>` / `# guarded-by: <lock>`"
                            + (
                                " (guard declared by annotation)"
                                if declared
                                else " (guard inferred)"
                            )
                        ),
                    )
                )
            # LOCK005: bare read in a method that elsewhere takes the lock
            for lineno, held, method, is_write in sites:
                if is_write or held:
                    continue
                if guard not in info.method_with_locks.get(method, ()):
                    continue
                findings.append(
                    Finding(
                        code="LOCK005",
                        path=m.rel,
                        line=lineno,
                        message=(
                            f"{info.name}.{attr} read with no lock held "
                            f"in {method}(), which takes {guard!r} "
                            "elsewhere — move the read under the lock, "
                            "or annotate `# lock-free: <reason>`"
                        ),
                    )
                )

    def _infer(
        self, sites: List[Tuple[int, FrozenSet[str], str, bool]]
    ) -> Optional[str]:
        writes = [s for s in sites if s[3]]
        if not writes:
            return None
        counts: Dict[str, int] = {}
        for _ln, held, _m, _w in writes:
            for lock in held:
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            return None
        guard, guarded = max(counts.items(), key=lambda kv: kv[1])
        unguarded = sum(1 for s in writes if guard not in s[1])
        if guarded < MIN_GUARDED_WRITES or guarded < unguarded:
            return None
        return guard
