"""Static analysis for pilosa_tpu: concurrency & JAX-purity gate.

Usage (programmatic — tools/check.py is the CLI):

    from pilosa_tpu import analysis
    result = analysis.check(repo_root, baseline_path)
    if not result.ok:
        print(result.render())

`default_passes()` is the registry: add a new pass by implementing
`framework.Pass` and appending it there (docs/development.md walks
through it).
"""

from __future__ import annotations

import os
from typing import List, Optional

from pilosa_tpu.analysis.api_invariants import ApiInvariantsPass
from pilosa_tpu.analysis.framework import (
    Baseline,
    BaselineEntry,
    Finding,
    GateResult,
    Module,
    Pass,
    load_modules,
    load_source_module,
    run_gate,
    run_passes,
)
from pilosa_tpu.analysis.guarded_by import GuardedByPass
from pilosa_tpu.analysis.jax_purity import JaxPurityPass
from pilosa_tpu.analysis.lifecycle import LifecyclePass
from pilosa_tpu.analysis.lock_hygiene import LockHygienePass

__all__ = [
    "ApiInvariantsPass",
    "Baseline",
    "BaselineEntry",
    "Finding",
    "GateResult",
    "GuardedByPass",
    "JaxPurityPass",
    "LifecyclePass",
    "LockHygienePass",
    "Module",
    "Pass",
    "check",
    "default_passes",
    "load_modules",
    "load_source_module",
    "run_gate",
    "run_passes",
]


def default_passes() -> List[Pass]:
    """The gate's pass registry, in execution order."""
    return [
        LockHygienePass(),
        GuardedByPass(),
        JaxPurityPass(),
        ApiInvariantsPass(),
        LifecyclePass(),
    ]


def check(
    root: Optional[str] = None,
    baseline_path: Optional[str] = None,
) -> GateResult:
    """Run the full gate over the package at `root` (default: the repo
    containing this installation) against the committed baseline."""
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    modules = load_modules(root)
    baseline = None
    if baseline_path is None:
        candidate = os.path.join(root, "tools", "analysis_baseline.toml")
        if os.path.exists(candidate):
            baseline_path = candidate
    if baseline_path is not None:
        baseline = Baseline.load(baseline_path)
    return run_gate(default_passes(), modules, baseline)
