"""Resource-lifecycle pass: must-release dataflow over the CFG.

The reference Pilosa gets release-on-every-path structurally from Go's
``defer``; this repo has to prove its try/finally discipline instead.
Every acquisition named in the declarative contract registry below
(CONTRACTS) must — on **every** CFG path out of the acquiring
function, exception edges included — be one of:

* released (a contract release method/function reaches the handle),
* returned to the caller (ownership transfer up),
* passed to a callee the contract declares takes ownership,
* stored into an attribute annotated as owning, or
* covered by an explicit annotation.

Annotations (reason mandatory, same contract as ``# lock-free:`` /
``# dispatch-ok:``; written trailing on the statement's first line or
as a one-line comment directly above it):

* ``# owns: <reason>``      — on an acquisition: don't track it (the
  surrounding object owns it); on an attribute store: the attribute
  owns the handle from here (its owner's shutdown path releases it).
* ``# releases: <reason>``  — this statement releases the tracked
  resource in a way the matcher can't see (indirect call, container
  drain).
* ``# transfer: <reason>``  — ownership leaves this function here
  (cross-function ledger, callee side-table) even though the callee
  isn't declared in the contract.

Rules:

* RES001 — a path to normal function exit may still hold the resource
  (includes an acquisition stored into an unannotated attribute
  outside ``__init__``).
* RES002 — a path to an escaping exception may still hold it.
* RES003 — the acquisition's handle is discarded at the call site.
* RES004 — annotation problems: empty reason, or an annotation that
  matched nothing (stale annotations must go, like stale baselines).
* RES005 — contract registry and the runtime ledger
  (utils/resources.py RESOURCE_CLASSES) out of sync, either way.

Scope and precision: the analysis is intraprocedural and tracks
single-name bindings (``x = acquire()``, including conditional
``x = acquire() if c else None``).  An acquisition used directly as a
``with`` context manager, returned immediately, or passed straight
into another call is ownership transfer by construction and is not
tracked.  ``if x is not None: x.release()`` style guards are
understood (branch pruning on identity/truth tests of the tracked
name, see cfg.CfgNode.true_entry).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pilosa_tpu.analysis.cfg import Cfg, CfgNode, build_cfg, iter_functions
from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
    import_aliases,
    resolve_call,
)
from pilosa_tpu.analysis.lock_hygiene import LOCKISH_RE

__all__ = ["Contract", "CONTRACTS", "LifecyclePass", "RESOURCES_MODULE"]

RESOURCES_MODULE = "pilosa_tpu/utils/resources.py"


@dataclass(frozen=True)
class Contract:
    """One resource class's lifecycle contract.

    ``acquire`` is a regex matched against the dotted call target of a
    candidate acquisition (both as written and alias-resolved, so
    ``from threading import Thread`` still matches ``threading.Thread``).

    ``mode`` selects what the dataflow tracks:
      var  — the name the acquisition is bound to (the handle);
      site — the acquisition site itself: the resource has no local
             handle (a pin refcount, an armed capture) and any
             downstream release-call/annotation settles it;
      recv — the call receiver (manual ``mu.acquire()``: the lock
             object is both handle and release target).
    """

    resource: str
    acquire: str
    prefilter: Tuple[str, ...]  # cheap terminal-name gate (speed only)
    mode: str = "var"
    release_methods: Tuple[str, ...] = ()  # handle.m(...)
    release_funcs: Tuple[str, ...] = ()  # f(handle) / site-mode any call
    transfer_funcs: Tuple[str, ...] = ()  # f(handle) takes ownership
    transfer_kwargs: Tuple[str, ...] = ()  # f(kw=handle) takes ownership
    require_kwargs: Tuple[Tuple[str, object], ...] = ()
    exempt_kwargs: Tuple[Tuple[str, object], ...] = ()
    check_return: bool = True  # normal exit while held is a leak
    check_raise: bool = True  # escaping exception while held is a leak
    paths: Tuple[str, ...] = ()  # rel-path prefixes; () = everywhere

    def acq_re(self) -> "re.Pattern[str]":
        return _RE_CACHE.setdefault(self.acquire, re.compile(self.acquire))


_RE_CACHE: Dict[str, "re.Pattern[str]"] = {}


# The declarative registry.  Every `resource` here must have an entry
# in utils/resources.py RESOURCE_CLASSES and vice versa (RES005).
CONTRACTS: Tuple[Contract, ...] = (
    Contract(
        resource="sched.ticket",
        acquire=r"(?:^|\.)(?:admit|_admit|_admit_transfer)$",
        prefilter=("admit", "_admit", "_admit_transfer"),
        release_methods=("release",),
    ),
    Contract(
        # the extent-table handle over a set of pinned keys
        resource="hbm.pin",
        acquire=r"(?:^|\.)ExtentTable$",
        prefilter=("ExtentTable",),
        release_methods=("release",),
        release_funcs=("release_extents",),
        transfer_kwargs=("extents", "table"),
    ),
    Contract(
        # a bare pin refcount taken without a table
        resource="hbm.pin",
        acquire=r"(?:^|\.)get_or_build$",
        prefilter=("get_or_build",),
        mode="site",
        require_kwargs=(("pin", True),),
        release_methods=("release",),
        release_funcs=("unpin", "unpin_all", "release_extents"),
    ),
    Contract(
        resource="hbm.pin",
        acquire=r"(?:^|\.)pin_if_present$",
        prefilter=("pin_if_present",),
        mode="site",
        release_methods=("release",),
        release_funcs=("unpin", "unpin_all", "release_extents"),
    ),
    Contract(
        # a group-commit position: the write is not acked until
        # wait_durable(token).  check_raise off: a raised write was
        # never acked, so there is nothing to wait for.
        resource="wal.token",
        acquire=r"(?:^|\.)_wal\.append(?:_many)?$|(?:^|\.)_wal_append$",
        prefilter=("append", "append_many", "_wal_append"),
        release_funcs=("wait_durable",),
        check_raise=False,
    ),
    Contract(
        resource="fragment.capture",
        acquire=r"(?:^|\.)begin_streaming$",
        prefilter=("begin_streaming",),
        mode="site",
        release_funcs=("end_capture",),
    ),
    Contract(
        resource="fault.plane",
        acquire=r"(?:^|\.)install_(?:injector|breakers)$",
        prefilter=("install_injector", "install_breakers"),
        mode="site",
        release_funcs=("uninstall_injector", "uninstall_breakers"),
    ),
    Contract(
        # tenant bucket charge: a DENIED admission must refund what an
        # earlier bucket granted.  check_return off: tokens granted on
        # the admit path are consumed by design.
        resource="tenant.charge",
        acquire=r"(?:^|\.)(?:qb|bb)\.take$",
        prefilter=("take",),
        mode="site",
        release_funcs=("refund",),
        check_return=False,
        paths=("pilosa_tpu/sched/",),
    ),
    Contract(
        resource="runtime.pool",
        acquire=r"(?:^|\.)ThreadPoolExecutor$|(?:^|\.)threading\.Thread$",
        prefilter=("ThreadPoolExecutor", "Thread"),
        release_methods=("shutdown", "join"),
        exempt_kwargs=(("daemon", True),),
    ),
    Contract(
        # a tracked lock acquired outside `with` must reach .release()
        # on every path — this is why `with` exists; bare acquires are
        # only for lexically-unprovable shapes (and get annotated)
        resource="lock.manual",
        acquire=r"\.acquire$",
        prefilter=("acquire",),
        mode="recv",
        release_methods=("release",),
    ),
)


# -- annotations ------------------------------------------------------------

_ANN_RE = re.compile(
    r"#\s*(?P<kind>owns|releases|transfer)\s*:\s*(?P<reason>[^#\n]*)"
)


@dataclass
class _Annotations:
    # lineno -> kind; empty-reason lines are reported once and then
    # treated as absent (they suppress nothing)
    by_line: Dict[int, str] = field(default_factory=dict)
    consumed: Set[int] = field(default_factory=set)
    findings: List[Finding] = field(default_factory=list)

    def claim(self, line: int) -> Optional[str]:
        """The annotation governing the statement starting at `line`:
        trailing on the line itself, or a comment on the line directly
        above (for statements too long to share a line with a reason).
        Claiming marks it consumed — unclaimed annotations are stale
        (RES004)."""
        for ln in (line, line - 1):
            if ln in self.by_line:
                self.consumed.add(ln)
                return self.by_line[ln]
        return None


def _comment_lines(source: str) -> List[Tuple[int, str]]:
    """(lineno, text) for every real comment token — docstrings and
    string literals that merely *mention* the annotation syntax (this
    module's own documentation, finding messages) don't count."""
    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenizeError, IndentationError, SyntaxError):
        pass  # unparseable files never reach the pass anyway
    return out


def _scan_annotations(module: Module) -> _Annotations:
    ann = _Annotations()
    for i, line in _comment_lines(module.source):
        m = _ANN_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        if not m.group("reason").strip():
            ann.findings.append(
                Finding(
                    "RES004",
                    module.rel,
                    i,
                    f"`# {kind}:` annotation has an empty reason — "
                    "ownership escapes must say why (same contract as "
                    "# lock-free:)",
                )
            )
            continue
        ann.by_line[i] = kind
    return ann


# -- acquisition detection --------------------------------------------------


def _kw_const(call: ast.Call, key: str) -> object:
    for kw in call.keywords:
        if kw.arg == key and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _call_matches(
    contract: Contract, call: ast.Call, aliases: Dict[str, str]
) -> bool:
    raw = dotted_name(call.func)
    if raw is None:
        return False
    pat = contract.acq_re()
    if not pat.search(raw):
        resolved = resolve_call(call, aliases)
        if resolved is None or not pat.search(resolved):
            return False
    for key, want in contract.require_kwargs:
        if _kw_const(call, key) != want:
            return False
    for key, want in contract.exempt_kwargs:
        if _kw_const(call, key) == want:
            return False
    if contract.mode == "recv":
        if not isinstance(call.func, ast.Attribute):
            return False
        recv = dotted_name(call.func.value)
        if recv is None or not LOCKISH_RE.search(recv.split(".")[-1]):
            return False
    return True


@dataclass
class _Acq:
    contract: Contract
    stmt: ast.stmt
    call: ast.Call
    var: Optional[str]  # var mode: the bound name; recv mode: receiver
    callee: str


def _matching_call(
    contract: Contract, value: Optional[ast.expr], aliases: Dict[str, str]
) -> Optional[ast.Call]:
    """The acquisition call when `value` is one (directly, or as either
    arm of a conditional expression)."""
    if isinstance(value, ast.Call) and _call_matches(contract, value, aliases):
        return value
    if isinstance(value, ast.IfExp):
        for arm in (value.body, value.orelse):
            if isinstance(arm, ast.Call) and _call_matches(
                contract, arm, aliases
            ):
                return arm
    return None


# -- kill / transfer matching ----------------------------------------------


def _name_in(expr: Optional[ast.AST], var: str) -> bool:
    if expr is None:
        return False
    return any(
        isinstance(n, ast.Name) and n.id == var for n in ast.walk(expr)
    )


def _node_exprs(node: CfgNode) -> List[ast.AST]:
    """The code that executes AT this node.  Compound-statement heads
    carry their whole subtree in ``stmt`` — only the head expression
    runs at the head node (a release inside an ``if`` body must NOT
    make the test a kill), and synthetic nodes run nothing."""
    stmt = node.stmt
    if stmt is None:
        return []
    if node.kind == "stmt":
        return [stmt]
    if node.kind == "branch":
        return [stmt.test]
    if node.kind == "loop":
        return [stmt.test if isinstance(stmt, ast.While) else stmt.iter]
    if node.kind == "with":
        return [item.context_expr for item in stmt.items]
    if node.kind == "match":
        return [stmt.subject]
    return []  # with_exit / except / handler / loop_exit / terminals


def _calls_in(exprs: Sequence[ast.AST]) -> List[ast.Call]:
    return [
        n
        for e in exprs
        for n in ast.walk(e)
        if isinstance(n, ast.Call)
    ]


def _kills(
    acq: _Acq, node: CfgNode, ann: _Annotations, in_init: bool
) -> bool:
    """Does executing `node` settle the tracked resource (release it,
    or transfer its ownership out of this function)?"""
    exprs = _node_exprs(node)
    if not exprs:
        return False
    stmt = node.stmt
    line = getattr(stmt, "lineno", 0)
    if ann.claim(line) is not None:
        return True
    c = acq.contract
    if c.mode == "site":
        for call in _calls_in(exprs):
            name = dotted_name(call.func)
            if name is None:
                continue
            term = name.split(".")[-1]
            if term in c.release_funcs or term in c.release_methods:
                return True
        return False
    if c.mode == "recv":
        for call in _calls_in(exprs):
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in c.release_methods
                and dotted_name(call.func.value) == acq.var
            ):
                return True
        return False
    # var mode
    var = acq.var
    assert var is not None
    if isinstance(stmt, ast.Return) and _name_in(stmt.value, var):
        return True  # ownership to the caller
    if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Name):
        if stmt.value.id == var and in_init:
            # self.attr = handle inside __init__: the instance owns it
            if all(isinstance(t, ast.Attribute) for t in stmt.targets):
                return True
    for call in _calls_in(exprs):
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in c.release_methods
            and dotted_name(func.value) == var
        ):
            return True
        name = dotted_name(func)
        term = name.split(".")[-1] if name else ""
        if term in c.release_funcs or term in c.transfer_funcs:
            if any(
                isinstance(a, ast.Name) and a.id == var for a in call.args
            ):
                return True
        for kw in call.keywords:
            if (
                kw.arg in c.transfer_kwargs
                and isinstance(kw.value, ast.Name)
                and kw.value.id == var
            ):
                return True
    return False


# -- branch pruning ---------------------------------------------------------


def _pruned_succs(node: CfgNode, var: Optional[str]) -> Set[int]:
    """Successors reachable while `var` still holds the (non-None)
    resource: identity/truth tests on the tracked name make one arm
    infeasible."""
    succs = node.succ | node.exc
    if var is None or node.kind != "branch" or node.true_entry is None:
        return succs
    test = node.stmt.test if isinstance(node.stmt, ast.If) else None
    if test is None:
        return succs
    true_when_held: Optional[bool] = None
    if isinstance(test, ast.Name) and test.id == var:
        true_when_held = True
    elif (
        isinstance(test, ast.UnaryOp)
        and isinstance(test.op, ast.Not)
        and isinstance(test.operand, ast.Name)
        and test.operand.id == var
    ):
        true_when_held = False
    elif (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and test.left.id == var
        and len(test.ops) == 1
        and len(test.comparators) == 1
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        if isinstance(test.ops[0], ast.Is):
            true_when_held = False
        elif isinstance(test.ops[0], ast.IsNot):
            true_when_held = True
    if true_when_held is True:
        return {node.true_entry} | node.exc
    if true_when_held is False:
        return succs - {node.true_entry}
    return succs


# -- the dataflow -----------------------------------------------------------


@dataclass
class _Leak:
    kind: str  # "exit" | "raise"
    witness: int  # line of the last statement before the escape


def _leak_paths(
    cfg: Cfg, acq: _Acq, ann: _Annotations, in_init: bool
) -> List[_Leak]:
    """Forward may-analysis for one acquisition: propagate "may still
    be held" from the acquisition's NORMAL out-edges (an acquire that
    raises acquired nothing) until a killing statement settles it
    (kills apply on both out-edges: the release happens even when the
    same statement later raises).  A held state reaching exit /
    raise_exit is a leak."""
    kill_cache: Dict[int, bool] = {}

    def kills(node: CfgNode) -> bool:
        if node.nid not in kill_cache:
            kill_cache[node.nid] = _kills(acq, node, ann, in_init)
        return kill_cache[node.nid]

    seeds: List[int] = []
    for node in cfg.stmt_nodes(acq.stmt):
        if kills(node):
            # the acquiring statement itself settles it (e.g. an
            # annotated acquisition line)
            continue
        seeds.extend(node.succ)

    var = acq.var if acq.contract.mode == "var" else None
    visited: Set[int] = set()
    parent: Dict[int, int] = {}
    work = list(dict.fromkeys(seeds))
    leaks: List[_Leak] = []
    for s in work:
        parent.setdefault(s, -1)
    while work:
        nid = work.pop()
        if nid in visited:
            continue
        visited.add(nid)
        node = cfg.node(nid)
        if nid == cfg.exit or nid == cfg.raise_exit:
            p = parent.get(nid, -1)
            witness = cfg.node(p).line if p >= 0 else acq.stmt.lineno
            leaks.append(
                _Leak("exit" if nid == cfg.exit else "raise", witness)
            )
            continue
        if kills(node):
            continue
        for s in _pruned_succs(node, var):
            if s not in visited:
                parent.setdefault(s, nid)
                work.append(s)
    return leaks


# -- the pass ---------------------------------------------------------------


def _fn_prefilter(fn: ast.AST, terms: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None and name.split(".")[-1] in terms:
                return True
    return False


def _resource_classes_decl(
    module: Module,
) -> Tuple[Set[str], int]:
    """Keys of the RESOURCE_CLASSES dict literal + its line."""
    for node in ast.walk(module.tree):
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not any(
            isinstance(t, ast.Name) and t.id == "RESOURCE_CLASSES"
            for t in targets
        ):
            continue
        if isinstance(value, ast.Dict):
            keys = {
                k.value
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
            return keys, node.lineno
    return set(), 1


class LifecyclePass(Pass):
    """CFG-based must-release analysis (see module docstring)."""

    name = "lifecycle"
    rules = ("RES001", "RES002", "RES003", "RES004", "RES005")

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        all_terms: Set[str] = set()
        for c in CONTRACTS:
            all_terms.update(c.prefilter)

        resources_mod: Optional[Module] = None
        for module in modules:
            if module.rel == RESOURCES_MODULE:
                resources_mod = module
            findings.extend(self._run_module(module, all_terms))

        findings.extend(self._cross_check(resources_mod))
        findings.sort(key=lambda f: (f.path, f.line, f.code))
        return findings

    # -- registry/ledger cross-check (RES005) --------------------------

    def _cross_check(self, resources_mod: Optional[Module]) -> List[Finding]:
        contracted = {c.resource for c in CONTRACTS}
        if resources_mod is None:
            return [
                Finding(
                    "RES005",
                    RESOURCES_MODULE,
                    1,
                    "runtime resource ledger module is missing — every "
                    "contracted resource class needs a ledger entry",
                )
            ]
        declared, line = _resource_classes_decl(resources_mod)
        out: List[Finding] = []
        for res in sorted(contracted - declared):
            out.append(
                Finding(
                    "RES005",
                    resources_mod.rel,
                    line,
                    f"resource class {res!r} has a lifecycle contract but "
                    "no RESOURCE_CLASSES ledger entry — the static pass "
                    "and the runtime ledger must stay in lockstep",
                )
            )
        for res in sorted(declared - contracted):
            out.append(
                Finding(
                    "RES005",
                    resources_mod.rel,
                    line,
                    f"ledger class {res!r} has no lifecycle contract — "
                    "delete the entry or add the contract",
                )
            )
        return out

    # -- per-module analysis -------------------------------------------

    def _run_module(
        self, module: Module, all_terms: Set[str]
    ) -> List[Finding]:
        ann = _scan_annotations(module)
        findings = list(ann.findings)
        aliases = import_aliases(module.tree)
        active = [
            c
            for c in CONTRACTS
            if not c.paths or module.rel.startswith(c.paths)
        ]
        if active:
            for qual, fn in iter_functions(module.tree):
                if not _fn_prefilter(fn, all_terms):
                    continue
                findings.extend(
                    self._run_function(module, qual, fn, active, ann, aliases)
                )
        for line in sorted(set(ann.by_line) - ann.consumed):
            findings.append(
                Finding(
                    "RES004",
                    module.rel,
                    line,
                    f"stale `# {ann.by_line[line]}:` annotation — it "
                    "suppresses no tracked acquisition on any path; "
                    "delete it (stale escapes rot like stale baselines)",
                )
            )
        return findings

    def _run_function(
        self,
        module: Module,
        qual: str,
        fn: ast.AST,
        contracts: Sequence[Contract],
        ann: _Annotations,
        aliases: Dict[str, str],
    ) -> List[Finding]:
        cfg = build_cfg(fn)
        in_init = fn.name == "__init__"
        seen_stmts: Dict[int, ast.stmt] = {}
        with_stmts: List[ast.stmt] = []
        for node in cfg.nodes:
            if node.stmt is not None and isinstance(node.stmt, ast.stmt):
                seen_stmts.setdefault(id(node.stmt), node.stmt)
                if node.kind == "with":
                    with_stmts.append(node.stmt)

        findings: List[Finding] = []
        emitted: Set[Tuple[str, int, str]] = set()
        for stmt in seen_stmts.values():
            for contract in contracts:
                for acq in self._acquisitions(
                    contract, stmt, with_stmts, aliases, ann, in_init,
                    module.rel, qual,
                ):
                    if isinstance(acq, Finding):
                        findings.append(acq)
                        continue
                    for leak in _leak_paths(cfg, acq, ann, in_init):
                        if leak.kind == "exit" and not contract.check_return:
                            continue
                        if leak.kind == "raise" and not contract.check_raise:
                            continue
                        code = "RES001" if leak.kind == "exit" else "RES002"
                        key = (code, acq.stmt.lineno, contract.resource)
                        if key in emitted:
                            continue
                        emitted.add(key)
                        how = (
                            "reaches normal exit"
                            if leak.kind == "exit"
                            else "escapes with an exception"
                        )
                        findings.append(
                            Finding(
                                code,
                                module.rel,
                                acq.stmt.lineno,
                                f"{contract.resource} acquired by "
                                f"`{acq.callee}` in {qual}() may leak: a "
                                f"path {how} (via line {leak.witness}) "
                                "without release/transfer — release on "
                                "every path or annotate with "
                                "# owns:/# releases:/# transfer: <reason>",
                            )
                        )
        return findings

    def _acquisitions(
        self,
        contract: Contract,
        stmt: ast.stmt,
        with_stmts: Sequence[ast.stmt],
        aliases: Dict[str, str],
        ann: _Annotations,
        in_init: bool,
        rel: str,
        qual: str,
    ):
        """Yield _Acq trackers and/or immediate Findings for one
        statement under one contract."""
        line = getattr(stmt, "lineno", 0)

        def annotated() -> bool:
            return ann.claim(line) is not None

        if stmt in with_stmts:
            return  # context manager releases by construction
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return  # ownership to the caller / unwinding anyway
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            call = _matching_call(contract, value, aliases)
            if call is None:
                return
            callee = dotted_name(call.func) or "?"
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if contract.mode == "site":
                if not annotated():
                    yield _Acq(contract, stmt, call, None, callee)
                return
            if contract.mode == "recv":
                if not annotated():
                    recv = dotted_name(call.func.value)  # type: ignore[attr-defined]
                    yield _Acq(contract, stmt, call, recv, callee)
                return
            if names:
                if annotated():
                    return
                yield _Acq(contract, stmt, call, names[0], callee)
                return
            # bound only to attributes: the object owns the handle —
            # provable in __init__, annotation-required elsewhere
            if in_init or annotated():
                return
            yield Finding(
                "RES001",
                rel,
                line,
                f"{contract.resource} acquired by `{callee}` in {qual}() "
                "is stored into an attribute outside __init__ without an "
                "ownership annotation — mark the store with "
                "# owns: <reason> (who shuts it down?) or keep a local "
                "handle and release it on every path",
            )
        elif isinstance(stmt, ast.Expr):
            call = _matching_call(contract, stmt.value, aliases)
            if call is None:
                return
            callee = dotted_name(call.func) or "?"
            if contract.mode == "site":
                if not annotated():
                    yield _Acq(contract, stmt, call, None, callee)
            elif contract.mode == "recv":
                if not annotated():
                    recv = dotted_name(call.func.value)  # type: ignore[attr-defined]
                    yield _Acq(contract, stmt, call, recv, callee)
            else:
                if annotated():
                    return
                yield Finding(
                    "RES003",
                    rel,
                    line,
                    f"{contract.resource} acquisition `{callee}` in "
                    f"{qual}() discards its handle — nothing can ever "
                    "release this; bind it and release on every path "
                    "(or annotate with # owns:/# transfer: <reason>)",
                )
