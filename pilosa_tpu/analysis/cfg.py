"""Intraprocedural control-flow graphs for the dataflow passes.

Builds a statement-level CFG per function (on top of the same parsed
``Module`` model framework.py gives every pass), precise enough for a
must-release analysis (analysis/lifecycle.py):

* branches, loops (with ``break``/``continue``/``else``), early
  returns, ``with`` blocks, ``match``;
* ``try``/``except``/``finally`` with **exception edges out of every
  statement that can raise**: a raising statement has an ``exc`` edge
  to the innermost handler dispatch, or through the enclosing
  ``finally`` bodies to the synthetic ``raise`` exit;
* ``finally`` bodies are cloned per continuation kind (fallthrough /
  raise / return / break / continue), lazily and memoized, so a
  release inside a ``finally`` kills the resource on *every* path that
  unwinds through it — exactly the guarantee the runtime gives;
* ``with`` bodies get the same unwind treatment via synthetic
  ``with_exit`` nodes (``__exit__`` runs on every way out).

Edge semantics: ``succ`` edges are normal completion, ``exc`` edges
are exception flow.  The distinction matters to clients only at effect
application time (an acquisition that raises acquired nothing); graph
reachability treats both uniformly.

The graph is conservative in the may-direction for leak analysis: an
exception edge exists whenever a statement *might* raise (calls,
subscripts, attribute access, imports, asserts, binary operators,
non-identity comparisons), and a ``with`` ``__exit__`` is never
assumed to suppress.  Identity tests (``x is None``) get no exception
edge, so the ubiquitous ``if x is not None: x.release()`` cleanup
idiom stays provable.  Branch nodes record their true-branch entry so
a dataflow client can prune branch arms that are infeasible for a
tracked value (see ``CfgNode.true_entry``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["CfgNode", "Cfg", "build_cfg", "iter_functions", "expr_can_raise"]

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass
class CfgNode:
    """One CFG node.  ``kind`` is one of:

    entry/exit/raise   — synthetic function boundaries (``raise`` is
                         the "an exception escaped" terminal);
    stmt               — a simple statement;
    branch             — an ``if`` test (``true_entry`` set);
    loop / loop_exit   — a ``for``/``while`` head and its join;
    with / with_exit   — a ``with`` enter and an ``__exit__`` run
                         (cloned per unwind kind);
    except / handler   — a try's handler dispatch and each clause;
    finally            — unused marker kind kept for clients; finally
                         bodies are real stmt nodes (cloned);
    match              — a ``match`` subject.
    """

    nid: int
    kind: str
    stmt: Optional[ast.AST] = None
    succ: Set[int] = field(default_factory=set)
    exc: Set[int] = field(default_factory=set)
    # for `branch` nodes: the node id the TRUE arm enters (every other
    # successor is reached by the test evaluating false)
    true_entry: Optional[int] = None

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)

    def edges(self) -> Set[int]:
        return self.succ | self.exc


class Cfg:
    """CFG of one function.  ``entry`` flows into the first statement;
    normal completion reaches ``exit``; an escaping exception reaches
    ``raise_exit``.  Statements may appear in several nodes (finally /
    with-exit bodies are cloned per unwind kind) — clients that key
    effects off statements should match on ``id(node.stmt)``."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.nodes: List[CfgNode] = []
        self.entry = self._new("entry")
        self.exit = self._new("exit")
        self.raise_exit = self._new("raise")

    def _new(self, kind: str, stmt: Optional[ast.AST] = None) -> int:
        node = CfgNode(len(self.nodes), kind, stmt)
        self.nodes.append(node)
        return node.nid

    def node(self, nid: int) -> CfgNode:
        return self.nodes[nid]

    def stmt_nodes(self, stmt: ast.AST) -> List[CfgNode]:
        """Every node carrying this exact statement object (clones
        included)."""
        return [n for n in self.nodes if n.stmt is stmt]


# -- can-raise predicate ----------------------------------------------------

_RAISER_NODES = (
    ast.Call,
    ast.Subscript,
    ast.Attribute,
    ast.Await,
    ast.Yield,
    ast.YieldFrom,
    ast.BinOp,
)


def _walk_expr(expr: ast.AST) -> Iterator[ast.AST]:
    # ast.walk, but without descending into deferred code (lambda
    # bodies run at call time, not here)
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(node, ast.Lambda) and child is node.body:
                continue
            stack.append(child)


def expr_can_raise(expr: Optional[ast.AST]) -> bool:
    """Conservative: may evaluating this expression raise?  Identity
    comparisons, boolean/unary ops and plain name/constant loads are
    the provably-quiet shapes; everything that can call user code
    (including operators and attribute access) can raise."""
    if expr is None:
        return False
    for node in _walk_expr(expr):
        if isinstance(node, _RAISER_NODES):
            return True
        if isinstance(node, ast.Compare) and not all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return True
    return False


def _stmt_can_raise(stmt: ast.stmt) -> bool:
    """Can-raise for SIMPLE statements (compound heads are handled
    per-shape in the builder)."""
    if isinstance(stmt, (ast.Pass, ast.Global, ast.Nonlocal)):
        return False
    if isinstance(stmt, (ast.Import, ast.ImportFrom, ast.Assert, ast.Raise)):
        return True
    if isinstance(stmt, ast.ClassDef):
        return True  # the class body executes at the statement
    if isinstance(stmt, FunctionNode):
        parts: List[ast.AST] = list(stmt.decorator_list)
        parts += stmt.args.defaults + [
            d for d in stmt.args.kw_defaults if d is not None
        ]
        return any(expr_can_raise(p) for p in parts)
    if isinstance(stmt, ast.Assign):
        return any(expr_can_raise(t) for t in stmt.targets) or expr_can_raise(
            stmt.value
        )
    if isinstance(stmt, ast.AnnAssign):
        return expr_can_raise(stmt.target) or expr_can_raise(stmt.value)
    if isinstance(stmt, ast.AugAssign):
        return True  # in-place operator dispatch
    if isinstance(stmt, ast.Return):
        return expr_can_raise(stmt.value)
    if isinstance(stmt, ast.Expr):
        return expr_can_raise(stmt.value)
    if isinstance(stmt, ast.Delete):
        return any(expr_can_raise(t) for t in stmt.targets)
    return True


_CATCH_ALL_NAMES = {"Exception", "BaseException"}


def _handler_catches_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types: List[ast.expr]
    if isinstance(handler.type, ast.Tuple):
        types = list(handler.type.elts)
    else:
        types = [handler.type]
    for t in types:
        name = t.attr if isinstance(t, ast.Attribute) else (
            t.id if isinstance(t, ast.Name) else ""
        )
        if name in _CATCH_ALL_NAMES:
            return True
    return False


# -- builder ----------------------------------------------------------------

_Target = Callable[[], int]


class _Ctx:
    """Where control transfers out of the current lexical region land.
    Targets are thunks: resolving one may lazily build the enclosing
    finally/with unwind clones on the way to the real destination."""

    __slots__ = ("raise_", "return_", "break_", "continue_")

    def __init__(
        self,
        raise_: _Target,
        return_: _Target,
        break_: Optional[_Target] = None,
        continue_: Optional[_Target] = None,
    ):
        self.raise_ = raise_
        self.return_ = return_
        self.break_ = break_
        self.continue_ = continue_


class _Builder:
    def __init__(self, cfg: Cfg):
        self.cfg = cfg

    # entries are always single nodes (the first statement of a block);
    # outs are the dangling normal-completion node ids of the block
    def build(self) -> None:
        cfg = self.cfg
        ctx = _Ctx(lambda: cfg.raise_exit, lambda: cfg.exit)
        entry, outs = self._block(self.cfg.fn.body, ctx)
        cfg.node(cfg.entry).succ.add(entry)
        self._wire(outs, cfg.exit)

    def _wire(self, preds: Sequence[int], target: int) -> None:
        for p in preds:
            self.cfg.node(p).succ.add(target)

    def _block(
        self, stmts: Sequence[ast.stmt], ctx: _Ctx
    ) -> Tuple[int, List[int]]:
        entry: Optional[int] = None
        outs: List[int] = []
        for stmt in stmts:
            s_entry, s_outs = self._stmt(stmt, ctx)
            if entry is None:
                entry = s_entry
            else:
                self._wire(outs, s_entry)
            outs = s_outs
        assert entry is not None  # Python blocks are never empty
        return entry, outs

    def _unwind_ctx(self, ctx: _Ctx, make: Callable[[int], int]) -> _Ctx:
        """Wrap `ctx` so any transfer out of the region first passes an
        unwind path built by make(ultimate_target) — a with_exit node
        or a finally-body clone.  One clone per transfer kind, built
        lazily and memoized (a finally with no `return` under it never
        grows a return clone)."""
        memo: Dict[str, int] = {}

        def via(kind: str, target: Optional[_Target]) -> Optional[_Target]:
            if target is None:
                return None

            def thunk() -> int:
                if kind not in memo:
                    memo[kind] = make(target())
                return memo[kind]

            return thunk

        raise_ = via("raise", ctx.raise_)
        return_ = via("return", ctx.return_)
        assert raise_ is not None and return_ is not None
        return _Ctx(
            raise_,
            return_,
            via("break", ctx.break_),
            via("continue", ctx.continue_),
        )

    def _stmt(self, stmt: ast.stmt, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            return self._if(stmt, ctx)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, ctx)
        if isinstance(stmt, ast.Try) or (
            hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)
        ):
            return self._try(stmt, ctx)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, ctx)

        n = cfg._new("stmt", stmt)
        if isinstance(stmt, ast.Return):
            if expr_can_raise(stmt.value):
                cfg.node(n).exc.add(ctx.raise_())
            cfg.node(n).succ.add(ctx.return_())
            return n, []
        if isinstance(stmt, ast.Raise):
            cfg.node(n).exc.add(ctx.raise_())
            return n, []
        if isinstance(stmt, ast.Break):
            if ctx.break_ is not None:
                cfg.node(n).succ.add(ctx.break_())
            return n, []
        if isinstance(stmt, ast.Continue):
            if ctx.continue_ is not None:
                cfg.node(n).succ.add(ctx.continue_())
            return n, []
        if _stmt_can_raise(stmt):
            cfg.node(n).exc.add(ctx.raise_())
        return n, [n]

    def _if(self, stmt: ast.If, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        n = cfg._new("branch", stmt)
        if expr_can_raise(stmt.test):
            cfg.node(n).exc.add(ctx.raise_())
        b_entry, b_outs = self._block(stmt.body, ctx)
        cfg.node(n).succ.add(b_entry)
        cfg.node(n).true_entry = b_entry
        outs = list(b_outs)
        if stmt.orelse:
            e_entry, e_outs = self._block(stmt.orelse, ctx)
            cfg.node(n).succ.add(e_entry)
            outs += e_outs
        else:
            outs.append(n)  # test-false falls through
        return n, outs

    def _loop(self, stmt: ast.stmt, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        head = cfg._new("loop", stmt)
        test = stmt.test if isinstance(stmt, ast.While) else stmt.iter
        if expr_can_raise(test) or not isinstance(stmt, ast.While):
            cfg.node(head).exc.add(ctx.raise_())
        lexit = cfg._new("loop_exit", stmt)
        loop_ctx = _Ctx(
            ctx.raise_, ctx.return_, lambda: lexit, lambda: head
        )
        b_entry, b_outs = self._block(stmt.body, loop_ctx)
        cfg.node(head).succ.add(b_entry)
        self._wire(b_outs, head)
        if stmt.orelse:
            # else runs on normal exhaustion (not break)
            o_entry, o_outs = self._block(stmt.orelse, ctx)
            cfg.node(head).succ.add(o_entry)
            self._wire(o_outs, lexit)
        else:
            cfg.node(head).succ.add(lexit)
        return head, [lexit]

    def _with(self, stmt: ast.stmt, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        enter = cfg._new("with", stmt)
        cfg.node(enter).exc.add(ctx.raise_())  # ctx exprs / __enter__

        def mk_exit(target: int) -> int:
            wx = cfg._new("with_exit", stmt)
            cfg.node(wx).succ.add(target)
            cfg.node(wx).exc.add(ctx.raise_())  # __exit__ itself
            return wx

        wctx = self._unwind_ctx(ctx, mk_exit)
        b_entry, b_outs = self._block(stmt.body, wctx)
        cfg.node(enter).succ.add(b_entry)
        wx = cfg._new("with_exit", stmt)
        cfg.node(wx).exc.add(ctx.raise_())
        self._wire(b_outs, wx)
        return enter, [wx]

    def _try(self, stmt: ast.stmt, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        if stmt.finalbody:
            def mk_finally(target: int) -> int:
                f_entry, f_outs = self._block(stmt.finalbody, ctx)
                self._wire(f_outs, target)
                return f_entry

            fctx = self._unwind_ctx(ctx, mk_finally)
        else:
            fctx = ctx

        if stmt.handlers:
            dispatch = cfg._new("except", stmt)
            body_ctx = _Ctx(
                lambda: dispatch, fctx.return_, fctx.break_, fctx.continue_
            )
        else:
            dispatch = None
            body_ctx = fctx

        b_entry, b_outs = self._block(stmt.body, body_ctx)
        normal_outs = list(b_outs)
        if stmt.orelse:
            # else-clause exceptions are NOT caught by this try
            o_entry, o_outs = self._block(stmt.orelse, fctx)
            self._wire(b_outs, o_entry)
            normal_outs = list(o_outs)

        if dispatch is not None:
            catch_all = False
            for handler in stmt.handlers:
                catch_all = catch_all or _handler_catches_all(handler)
                h = cfg._new("handler", handler)
                cfg.node(dispatch).succ.add(h)
                hb_entry, hb_outs = self._block(handler.body, fctx)
                cfg.node(h).succ.add(hb_entry)
                normal_outs += hb_outs
            if not catch_all:
                # an exception no clause matches escapes (through the
                # finally, when there is one)
                cfg.node(dispatch).exc.add(fctx.raise_())

        if stmt.finalbody:
            f_entry, f_outs = self._block(stmt.finalbody, ctx)
            self._wire(normal_outs, f_entry)
            return b_entry, f_outs
        return b_entry, normal_outs

    def _match(self, stmt: ast.Match, ctx: _Ctx) -> Tuple[int, List[int]]:
        cfg = self.cfg
        n = cfg._new("match", stmt)
        guards = [c.guard for c in stmt.cases if c.guard is not None]
        if expr_can_raise(stmt.subject) or any(map(expr_can_raise, guards)):
            cfg.node(n).exc.add(ctx.raise_())
        outs: List[int] = [n]  # no case may match
        for case in stmt.cases:
            c_entry, c_outs = self._block(case.body, ctx)
            cfg.node(n).succ.add(c_entry)
            outs += c_outs
        return n, outs


def build_cfg(fn: ast.AST) -> Cfg:
    """Build the CFG of one (async) function definition."""
    cfg = Cfg(fn)
    _Builder(cfg).build()
    return cfg


def iter_functions(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.AST]]:
    """Yield (qualname, fn) for every function/method in the module,
    nested ones included."""

    def walk(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FunctionNode):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from walk(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")
