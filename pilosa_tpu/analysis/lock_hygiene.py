"""Lock-hygiene pass: what may be constructed, and what may run under a lock.

Rules (codes):

* LOCK001 — raw `threading.Lock()` / `RLock()` / `Condition()` /
  `Semaphore()` constructed anywhere except `pilosa_tpu/utils/locks.py`.
  All locks go through the tracked factories so the runtime deadlock
  checker sees them.
* LOCK002 — blocking host work inside a `with <lock>:` body: `time.sleep`,
  `subprocess.*`, socket connect/IO, `urllib`/`http.client`/`requests`
  network calls. A lock held across a sleep or the network turns every
  peer timeout into whole-process convoying (and starved the XLA
  dispatch path once already — see PR 1's deadlock note).
* LOCK003 — device synchronization inside a `with <lock>:` body:
  `.block_until_ready()`, `jax.device_get`, `jax.device_put`. Holding a
  lock through a device round-trip serializes all query threads behind
  HBM latency; where that is *intentional* (exec/plan.py serializes the
  whole mesh dispatch by design) the site is baselined with a reason,
  not rewritten.

Scope notes: bodies of functions *defined* under a `with` are skipped
(closures run later, lock not necessarily held); lock detection is
name-based (`*_mu`, `*_lock`, `*_once`, `_MU`/`_LOCK` globals — the
repo-wide naming convention the tracked factories enforce by usage).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence

from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
    import_aliases,
    resolve_call,
)

__all__ = ["LockHygienePass", "LOCKISH_RE"]

# terminal identifier of a with-context expression that names a mutex
LOCKISH_RE = re.compile(r"(?:^|_)(?:mu|mutex|lock|lk|once)\d*$", re.IGNORECASE)

_RAW_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

# dotted-origin prefixes that mean "blocking host work" under a lock
_BLOCKING_ORIGINS = (
    "time.sleep",
    "subprocess.",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.",
    "http.client.",
    "requests.",
)

_DEVICE_SYNC_ORIGINS = (
    "jax.device_get",
    "jax.device_put",
    "jax.block_until_ready",
)

_ALLOWED_RAW_IN = "pilosa_tpu/utils/locks.py"


def _lockish(expr: ast.AST) -> Optional[str]:
    """Name of the lock when `expr` looks like one, else None."""
    name = dotted_name(expr)
    if name is None:
        return None
    terminal = name.rsplit(".", 1)[-1]
    return name if LOCKISH_RE.search(terminal) else None


class _UnderLockScanner(ast.NodeVisitor):
    """Scan a with-body for forbidden calls, skipping deferred bodies."""

    def __init__(
        self,
        pass_: "LockHygienePass",
        module: Module,
        aliases: Dict[str, str],
        lock_name: str,
        findings: List[Finding],
    ):
        self.pass_ = pass_
        self.module = module
        self.aliases = aliases
        self.lock_name = lock_name
        self.findings = findings

    # closures / nested defs run after the lock is released
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        origin = resolve_call(node, self.aliases)
        device_sync_hit = False
        if origin is not None:
            for bad in _BLOCKING_ORIGINS:
                if origin == bad or (bad.endswith(".") and origin.startswith(bad)):
                    self.findings.append(
                        Finding(
                            code="LOCK002",
                            path=self.module.rel,
                            line=node.lineno,
                            message=(
                                f"blocking call {origin}() inside "
                                f"`with {self.lock_name}:` body"
                            ),
                        )
                    )
                    break
            for bad in _DEVICE_SYNC_ORIGINS:
                if origin == bad:
                    device_sync_hit = True
                    self.findings.append(
                        Finding(
                            code="LOCK003",
                            path=self.module.rel,
                            line=node.lineno,
                            message=(
                                f"device sync {origin}() inside "
                                f"`with {self.lock_name}:` body"
                            ),
                        )
                    )
        # method-style device sync: <expr>.block_until_ready()
        # (skipped when the origin match above already reported this call
        # as function-style jax.block_until_ready)
        if (
            not device_sync_hit
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            self.findings.append(
                Finding(
                    code="LOCK003",
                    path=self.module.rel,
                    line=node.lineno,
                    message=(
                        "device sync .block_until_ready() inside "
                        f"`with {self.lock_name}:` body"
                    ),
                )
            )
        self.generic_visit(node)


class LockHygienePass(Pass):
    name = "lock-hygiene"

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        for m in modules:
            aliases = import_aliases(m.tree)
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call):
                    self._check_raw_ctor(m, node, aliases, findings)
                elif isinstance(node, ast.With):
                    self._check_with(m, node, aliases, findings)
        return findings

    def _check_raw_ctor(
        self,
        m: Module,
        node: ast.Call,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        if m.rel.endswith(_ALLOWED_RAW_IN):
            return
        origin = resolve_call(node, aliases)
        if origin in _RAW_LOCK_CTORS:
            short = origin.rsplit(".", 1)[-1]
            findings.append(
                Finding(
                    code="LOCK001",
                    path=m.rel,
                    line=node.lineno,
                    message=(
                        f"raw threading.{short}() constructed outside "
                        "utils/locks.py — use locks.TrackedLock/"
                        "TrackedRLock/TrackedCondition so the deadlock "
                        "checker sees it"
                    ),
                )
            )

    def _check_with(
        self,
        m: Module,
        node: ast.With,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        lock_names = [
            n
            for n in (_lockish(item.context_expr) for item in node.items)
            if n is not None
        ]
        if not lock_names:
            return
        scanner = _UnderLockScanner(
            self, m, aliases, lock_names[0], findings
        )
        for stmt in node.body:
            scanner.visit(stmt)
