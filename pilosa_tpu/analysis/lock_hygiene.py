"""Lock-hygiene pass: what may be constructed, and what may run under a lock.

Rules (codes):

* LOCK001 — raw `threading.Lock()` / `RLock()` / `Condition()` /
  `Semaphore()` constructed anywhere except `pilosa_tpu/utils/locks.py`.
  All locks go through the tracked factories so the runtime deadlock
  checker sees them.
* LOCK002 — blocking host work inside a `with <lock>:` body: `time.sleep`,
  `subprocess.*`, socket connect/IO, `urllib`/`http.client`/`requests`
  network calls. A lock held across a sleep or the network turns every
  peer timeout into whole-process convoying (and starved the XLA
  dispatch path once already — see PR 1's deadlock note).
* LOCK003 — device synchronization inside a `with <lock>:` body:
  `.block_until_ready()`, `jax.device_get`, `jax.device_put`. Holding a
  lock through a device round-trip serializes all query threads behind
  HBM latency; where that is *intentional* (exec/plan.py serializes the
  whole mesh dispatch by design) the site is baselined with a reason,
  not rewritten.
* LOCK006 — dispatch discipline (the PR-10 deadlock class): in
  `pilosa_tpu/exec/`, `pilosa_tpu/ops/` and `pilosa_tpu/hbm/`, a call
  to a `jax.jit`-compiled function (discovered across the whole module
  set) or a `.block_until_ready()` wait must be lexically inside
  `with <dispatch mutex>:` (`plan._DISPATCH_MU` / `plan.dispatch_mutex()`)
  or inside a closure handed to `plan.run_serialized(...)`. Concurrent
  entry into collective-bearing compiled programs parks XLA-CPU's
  rendezvous when virtual devices outnumber cores — PR 1 fixed it for
  plans, PR 10 re-fixed it for tally/aggregate dispatches; this rule is
  the machine-checked form of that convention. Calls inside OTHER
  traced bodies are exempt (jit-of-jit inlines into one program).
* LOCK007 — durability waits under a fragment-class lock (the PR-11
  convention): in `pilosa_tpu/core/`, `os.fsync` / `.fsync()` /
  `GROUP_COMMIT.wait_durable()` / `GROUP_COMMIT.flush()` /
  `write_snapshot()` / `<wal>.truncate()` must not run lexically inside
  a `with self.<lock>:` body — a strict-mode fsync round under
  `fragment.mu` serializes every reader and writer of that fragment
  behind disk latency AND defeats cross-caller group-commit
  coalescing. Commit tokens are returned past the lock and waited
  there (`import_positions` / `stage_positions`); the snapshot path's
  fsyncs are the designed exception, baselined with the reason.

Scope notes: bodies of functions *defined* under a `with` are skipped
(closures run later, lock not necessarily held); lock detection is
name-based (`*_mu`, `*_lock`, `*_once`, `_MU`/`_LOCK` globals — the
repo-wide naming convention the tracked factories enforce by usage).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set

from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
    import_aliases,
    resolve_call,
)

__all__ = ["LockHygienePass", "LOCKISH_RE"]

# terminal identifier of a with-context expression that names a mutex
LOCKISH_RE = re.compile(r"(?:^|_)(?:mu|mutex|lock|lk|once)\d*$", re.IGNORECASE)

_RAW_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

# dotted-origin prefixes that mean "blocking host work" under a lock
_BLOCKING_ORIGINS = (
    "time.sleep",
    "subprocess.",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.",
    "http.client.",
    "requests.",
)

_DEVICE_SYNC_ORIGINS = (
    "jax.device_get",
    "jax.device_put",
    "jax.block_until_ready",
)

# raw threading primitives are permitted in the checker substrate itself:
# locks.py IS the tracked factory, and race.py's internal bookkeeping
# mutexes must stay invisible to the lockset they are computing (a
# tracked tracker lock would appear in every access's held set)
_ALLOWED_RAW_IN = (
    "pilosa_tpu/utils/locks.py",
    "pilosa_tpu/utils/race.py",
    # the resource ledger is checker substrate like locks/race: its one
    # mutex must not feed the lock-order graph it helps to police
    "pilosa_tpu/utils/resources.py",
)

# -- LOCK006: dispatch discipline -------------------------------------------

# modules where compiled dispatches live and the one-program-at-a-time
# rule applies (the PR-10 deadlock class)
_DISPATCH_SCOPE = (
    "pilosa_tpu/exec/",
    "pilosa_tpu/ops/",
    "pilosa_tpu/hbm/",
)

# a with-context satisfying the discipline: the dispatch mutex itself
# (by its conventional names) or anything acquired via dispatch_mutex()
_DISPATCH_MUTEX_RE = re.compile(r"dispatch_*(mu|mutex)$", re.IGNORECASE)

_RUN_SERIALIZED_NAMES = ("run_serialized", "run_counted")

# `# dispatch-ok: <reason>` annotation: on a call line it exempts that
# call, on a `def` line the whole function body. For the three shapes
# lexical analysis cannot prove safe: trace-time helpers (called only
# during jit tracing, inlined into the one program), forwarding wrappers
# (ops/ functions whose job IS the compiled call — their callers
# serialize), and single-device paths with no collectives to rendezvous.
# The reason is mandatory; an empty one is itself a LOCK006 finding.
_DISPATCH_OK_RE = re.compile(r"#\s*dispatch-ok\s*:\s*(?P<arg>[^#\n]*)")


def _dispatch_ok_lines(source: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DISPATCH_OK_RE.search(line)
        if m:
            out[i] = m.group("arg").strip()
    return out

# -- LOCK007: durability waits under a fragment-class lock ------------------

_FRAGMENT_LOCK_SCOPE = "pilosa_tpu/core/"

# call shapes that fsync or block on a WAL commit round
_DURABILITY_ORIGINS = ("os.fsync",)
_DURABILITY_ATTRS = ("fsync", "_fsync", "wait_durable")
# helpers known to fsync internally (file + directory)
_DURABILITY_HELPERS = ("write_snapshot",)


def _lockish(expr: ast.AST) -> Optional[str]:
    """Name of the lock when `expr` looks like one, else None."""
    name = dotted_name(expr)
    if name is None:
        return None
    terminal = name.rsplit(".", 1)[-1]
    return name if LOCKISH_RE.search(terminal) else None


class _UnderLockScanner(ast.NodeVisitor):
    """Scan a with-body for forbidden calls, skipping deferred bodies."""

    def __init__(
        self,
        pass_: "LockHygienePass",
        module: Module,
        aliases: Dict[str, str],
        lock_name: str,
        findings: List[Finding],
    ):
        self.pass_ = pass_
        self.module = module
        self.aliases = aliases
        self.lock_name = lock_name
        self.findings = findings

    # closures / nested defs run after the lock is released
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        origin = resolve_call(node, self.aliases)
        device_sync_hit = False
        if origin is not None:
            for bad in _BLOCKING_ORIGINS:
                if origin == bad or (bad.endswith(".") and origin.startswith(bad)):
                    self.findings.append(
                        Finding(
                            code="LOCK002",
                            path=self.module.rel,
                            line=node.lineno,
                            message=(
                                f"blocking call {origin}() inside "
                                f"`with {self.lock_name}:` body"
                            ),
                        )
                    )
                    break
            for bad in _DEVICE_SYNC_ORIGINS:
                if origin == bad:
                    device_sync_hit = True
                    self.findings.append(
                        Finding(
                            code="LOCK003",
                            path=self.module.rel,
                            line=node.lineno,
                            message=(
                                f"device sync {origin}() inside "
                                f"`with {self.lock_name}:` body"
                            ),
                        )
                    )
        # method-style device sync: <expr>.block_until_ready()
        # (skipped when the origin match above already reported this call
        # as function-style jax.block_until_ready)
        if (
            not device_sync_hit
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            self.findings.append(
                Finding(
                    code="LOCK003",
                    path=self.module.rel,
                    line=node.lineno,
                    message=(
                        "device sync .block_until_ready() inside "
                        f"`with {self.lock_name}:` body"
                    ),
                )
            )
        self.generic_visit(node)


def _jitted_names(modules: Sequence[Module]) -> Dict[str, Set[str]]:
    """module rel -> set of function names compiled by jax.jit in that
    module (decorator or `X = jax.jit(fn)` forms). The caller resolves
    cross-module calls by mapping a call origin's dotted module prefix
    back to a rel path."""
    out: Dict[str, Set[str]] = {}
    for m in modules:
        aliases = import_aliases(m.tree)
        names: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if _is_jit_decorator(dec, aliases):
                        names.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if resolve_call(node.value, aliases) == "jax.jit":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
        out[m.rel] = names
    return out


def _is_jit_decorator(dec: ast.AST, aliases: Dict[str, str]) -> bool:
    """@jax.jit, @jit, @partial(jax.jit, ...), @functools.partial(jax.jit,
    ...), @jax.jit(...)."""

    def is_jit(node: ast.AST) -> bool:
        name = dotted_name(node)
        if name is None:
            return False
        head, _, rest = name.partition(".")
        origin = aliases.get(head, head)
        return (f"{origin}.{rest}" if rest else origin) == "jax.jit"

    if is_jit(dec):
        return True
    if isinstance(dec, ast.Call):
        origin = resolve_call(dec, aliases)
        if origin in ("functools.partial", "partial"):
            return bool(dec.args) and is_jit(dec.args[0])
        return is_jit(dec.func)
    return False


def _rel_to_dotted(rel: str) -> str:
    return rel[: -len(".py")].replace("/", ".") if rel.endswith(".py") else rel


def _is_dispatch_mutex_ctx(expr: ast.AST) -> bool:
    """`with _DISPATCH_MU:` / `with plan.dispatch_mutex():` — the
    contexts that satisfy LOCK006."""
    target = expr.func if isinstance(expr, ast.Call) else expr
    name = dotted_name(target)
    if name is None:
        return False
    return bool(_DISPATCH_MUTEX_RE.search(name.rsplit(".", 1)[-1]))


class _DispatchScanner(ast.NodeVisitor):
    """LOCK006 walker for one exec/ops/hbm module: flags compiled calls
    and block_until_ready waits outside a dispatch-mutex context.
    Deferred bodies (closures, lambdas) are scanned only when they are
    arguments to run_serialized — where they are exempt by definition —
    otherwise skipped like every other hygiene rule."""

    def __init__(
        self,
        m: Module,
        aliases: Dict[str, str],
        local_jitted: Set[str],
        jitted_by_dotted: Dict[str, Set[str]],
        findings: List[Finding],
    ):
        self.m = m
        self.aliases = aliases
        self.local_jitted = local_jitted
        self.jitted_by_dotted = jitted_by_dotted
        self.findings = findings
        self.ok_lines = _dispatch_ok_lines(m.source)

    def _annotated_ok(self, lineno: int) -> bool:
        reason = self.ok_lines.get(lineno)
        if reason is None:
            return False
        if not reason:
            self.findings.append(
                Finding(
                    code="LOCK006",
                    path=self.m.rel,
                    line=lineno,
                    message=(
                        "`# dispatch-ok:` annotation has no reason — say "
                        "WHY this compiled call is safe outside the "
                        "dispatch mutex"
                    ),
                )
            )
        return True

    # traced bodies: a jit-compiled function calling another jitted
    # function inlines it into one program — no separate dispatch
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name in self.local_jitted:
            return
        if any(_is_jit_decorator(d, self.aliases) for d in node.decorator_list):
            return
        if self._annotated_ok(node.lineno):
            return
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_With(self, node: ast.With) -> None:
        if any(_is_dispatch_mutex_ctx(i.context_expr) for i in node.items):
            return  # everything under the dispatch mutex is disciplined
        self.generic_visit(node)

    def _is_compiled_call(self, node: ast.Call) -> Optional[str]:
        origin = resolve_call(node, self.aliases)
        if origin is None:
            return None
        head, _, tail = origin.rpartition(".")
        if not head:
            # bare local name
            return origin if origin in self.local_jitted else None
        if head in self.jitted_by_dotted and tail in self.jitted_by_dotted[head]:
            return origin
        return None

    def visit_Call(self, node: ast.Call) -> None:
        # run_serialized(fn)/run_serialized(lambda: ...): DEFERRED
        # callables (lambdas, named function refs) run under the
        # dispatch mutex by construction and are exempt — but any other
        # argument expression evaluates EAGERLY on the calling thread
        # before run_serialized runs, so run_serialized(_tally(x)) is
        # exactly the PR-10 bug wearing the fix's clothes: keep scanning
        # those.
        callee = dotted_name(node.func)
        if callee is not None and callee.rsplit(".", 1)[-1] in _RUN_SERIALIZED_NAMES:
            for arg in node.args:
                if not isinstance(arg, (ast.Lambda, ast.Name)):
                    self.visit(arg)
            for kw in node.keywords:
                if not isinstance(kw.value, (ast.Lambda, ast.Name)):
                    self.visit(kw.value)
            return
        if self._annotated_ok(node.lineno):
            self.generic_visit(node)
            return
        origin = resolve_call(node, self.aliases)
        compiled = self._is_compiled_call(node)
        if compiled is not None:
            self.findings.append(
                Finding(
                    code="LOCK006",
                    path=self.m.rel,
                    line=node.lineno,
                    message=(
                        f"compiled dispatch {compiled}() outside "
                        "plan.run_serialized/dispatch_mutex — concurrent "
                        "collective-bearing programs deadlock the XLA "
                        "rendezvous (the PR-10 class); route it through "
                        "run_serialized or hold dispatch_mutex()"
                    ),
                )
            )
        elif origin == "jax.block_until_ready" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            self.findings.append(
                Finding(
                    code="LOCK006",
                    path=self.m.rel,
                    line=node.lineno,
                    message=(
                        "block_until_ready() outside plan.run_serialized/"
                        "dispatch_mutex — a compiled program's completion "
                        "wait must stay under the one-program-at-a-time "
                        "mutex (the PR-10 class)"
                    ),
                )
            )
        self.generic_visit(node)


class _FragmentLockScanner(ast.NodeVisitor):
    """LOCK007 walker over a `with self.<lock>:` body in core/: flags
    fsync / commit-wait calls made while the lock is held. Deferred
    bodies are skipped (same closure rule as LOCK002/003)."""

    def __init__(self, m: Module, aliases: Dict[str, str],
                 lock_name: str, findings: List[Finding]):
        self.m = m
        self.aliases = aliases
        self.lock_name = lock_name
        self.findings = findings

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        origin = resolve_call(node, self.aliases)
        flagged: Optional[str] = None
        if origin in _DURABILITY_ORIGINS:
            flagged = origin
        elif origin is not None and origin.rsplit(".", 1)[-1] in _DURABILITY_HELPERS:
            flagged = origin
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = dotted_name(node.func.value) or ""
            if attr in _DURABILITY_ATTRS:
                flagged = f"{recv}.{attr}" if recv else attr
            elif attr in ("truncate", "flush") and recv.rsplit(".", 1)[
                -1
            ].lower().lstrip("_").startswith(("wal", "group_commit")):
                flagged = f"{recv}.{attr}"
        if flagged is not None:
            self.findings.append(
                Finding(
                    code="LOCK007",
                    path=self.m.rel,
                    line=node.lineno,
                    message=(
                        f"durability call {flagged}() inside "
                        f"`with {self.lock_name}:` — fsync/commit waits "
                        "under a fragment-class lock serialize readers "
                        "behind disk latency and defeat group-commit "
                        "coalescing (the PR-11 convention: return the "
                        "commit token past the lock and wait there)"
                    ),
                )
            )
        self.generic_visit(node)


class LockHygienePass(Pass):
    name = "lock-hygiene"
    rules = (
        "LOCK001", "LOCK002", "LOCK003", "LOCK006", "LOCK007",
    )

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        jitted = _jitted_names(modules)
        jitted_by_dotted = {
            _rel_to_dotted(rel): names for rel, names in jitted.items() if names
        }
        for m in modules:
            aliases = import_aliases(m.tree)
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call):
                    self._check_raw_ctor(m, node, aliases, findings)
                elif isinstance(node, ast.With):
                    self._check_with(m, node, aliases, findings)
            if m.rel.startswith(_DISPATCH_SCOPE):
                scanner = _DispatchScanner(
                    m, aliases, jitted.get(m.rel, set()),
                    jitted_by_dotted, findings,
                )
                scanner.visit(m.tree)
        return findings

    def _check_raw_ctor(
        self,
        m: Module,
        node: ast.Call,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        if m.rel.endswith(_ALLOWED_RAW_IN):
            return
        origin = resolve_call(node, aliases)
        if origin in _RAW_LOCK_CTORS:
            short = origin.rsplit(".", 1)[-1]
            findings.append(
                Finding(
                    code="LOCK001",
                    path=m.rel,
                    line=node.lineno,
                    message=(
                        f"raw threading.{short}() constructed outside "
                        "utils/locks.py — use locks.TrackedLock/"
                        "TrackedRLock/TrackedCondition so the deadlock "
                        "checker sees it"
                    ),
                )
            )

    def _check_with(
        self,
        m: Module,
        node: ast.With,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        lock_names = [
            n
            for n in (_lockish(item.context_expr) for item in node.items)
            if n is not None
        ]
        if not lock_names:
            return
        scanner = _UnderLockScanner(
            self, m, aliases, lock_names[0], findings
        )
        for stmt in node.body:
            scanner.visit(stmt)
        # LOCK007: in core/, a `with self.<lock>:` body (the
        # fragment-class lock convention) must not fsync or wait on a
        # commit round
        if m.rel.startswith(_FRAGMENT_LOCK_SCOPE):
            self_locks = [
                n for n in lock_names if n.startswith("self.")
            ]
            if self_locks:
                frag_scanner = _FragmentLockScanner(
                    m, aliases, self_locks[0], findings
                )
                for stmt in node.body:
                    frag_scanner.visit(stmt)
