"""JAX-purity pass: traced code must be pure and statically shaped.

Functions compiled by `jax.jit` (and kernels handed to `pl.pallas_call`)
are traced once and replayed: Python side effects inside them run at
trace time only (silently wrong), host-numpy calls force device->host
transfers or break tracing, and scalar coercions (`.item()`, `int(x)` on
a traced value) force a blocking device read per call.

Rules (codes):

* JAX001 — Python side effect in a traced body: `print(...)` or a
  `global` statement.
* JAX002 — host numpy call (`np.*` / `numpy.*`) in a traced body.
* JAX003 — traced->host coercion in a traced body: `.item()`, or
  `int()/float()/bool()` applied to a non-static parameter.
* JAX004 — mutation of module-level state (subscript/attribute store on
  a module global) in a traced body; trace-time mutation runs once, not
  per call.
* JAX005 — `static_argnums` index out of range or `static_argnames`
  naming a parameter the function does not have (the jit would raise at
  call time — or worse, silently mark nothing static).
* JAX006 — wall-clock / RNG host calls (`time.*`, `random.*`) in a
  traced body.

Traced bodies are discovered from: `@jax.jit`, `@jit`,
`@partial(jax.jit, ...)` / `@functools.partial(jax.jit, ...)`,
`jax.jit(fn, ...)` call expressions over local function names, and
function names (possibly wrapped in `functools.partial`) passed as the
first argument to `pl.pallas_call`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
    import_aliases,
    resolve_call,
)

__all__ = ["JaxPurityPass"]

_JIT_ORIGINS = {"jax.jit"}
_PARTIAL_ORIGINS = {"functools.partial", "partial"}
_PALLAS_CALL_ORIGINS = {"jax.experimental.pallas.pallas_call"}


def _is_jit_target(node: ast.AST, aliases: Dict[str, str]) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    full = f"{origin}.{rest}" if rest else origin
    return full in _JIT_ORIGINS


def _static_spec(
    call: ast.Call,
) -> Tuple[Optional[List[int]], Optional[List[str]]]:
    """Extract literal static_argnums / static_argnames from a jit-ish
    call's keywords (None when absent or non-literal)."""
    nums: Optional[List[int]] = None
    names: Optional[List[str]] = None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _int_literals(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_literals(kw.value)
    return nums, names


def _int_literals(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return None
        return out
    return None


def _str_literals(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
            else:
                return None
        return out
    return None


class _TracedBody:
    def __init__(
        self,
        fn: ast.FunctionDef,
        static_names: Set[str],
        kind: str,  # "jit" | "pallas-kernel"
    ):
        self.fn = fn
        self.static_names = static_names
        self.kind = kind


class JaxPurityPass(Pass):
    name = "jax-purity"
    rules = ("JAX001", "JAX002", "JAX003", "JAX004", "JAX005", "JAX006")

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        for m in modules:
            aliases = import_aliases(m.tree)
            defs = {
                n.name: n
                for n in ast.walk(m.tree)
                if isinstance(n, ast.FunctionDef)
            }
            traced = self._discover(m, aliases, defs, findings)
            globals_ = self._module_globals(m.tree)
            for body in traced:
                self._check_body(m, aliases, body, globals_, findings)
        return findings

    # -- discovery ---------------------------------------------------------

    def _discover(
        self,
        m: Module,
        aliases: Dict[str, str],
        defs: Dict[str, ast.FunctionDef],
        findings: List[Finding],
    ) -> List[_TracedBody]:
        traced: List[_TracedBody] = []
        seen: Set[str] = set()

        def add(fn: ast.FunctionDef, static: Set[str], kind: str) -> None:
            if fn.name not in seen:
                seen.add(fn.name)
                traced.append(_TracedBody(fn, static, kind))

        for fn in defs.values():
            for dec in fn.decorator_list:
                if _is_jit_target(dec, aliases):
                    add(fn, set(), "jit")
                elif isinstance(dec, ast.Call):
                    static = self._jit_call_statics(
                        m, dec, aliases, fn, findings
                    )
                    if static is not None:
                        add(fn, static, "jit")
        # jax.jit(fn, ...) expressions and pallas_call(kernel, ...) args
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = resolve_call(node, aliases)
            if origin in _JIT_ORIGINS and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name) and target.id in defs:
                    static = self._statics_for(
                        m, node, defs[target.id], findings
                    )
                    add(defs[target.id], static, "jit")
            elif (
                origin in _PALLAS_CALL_ORIGINS
                or (origin or "").endswith(".pallas_call")
            ) and node.args:
                kernel = node.args[0]
                if (
                    isinstance(kernel, ast.Call)
                    and resolve_call(kernel, aliases) in _PARTIAL_ORIGINS
                    and kernel.args
                ):
                    kernel = kernel.args[0]
                if isinstance(kernel, ast.Name) and kernel.id in defs:
                    add(defs[kernel.id], set(), "pallas-kernel")
        return traced

    def _jit_call_statics(
        self,
        m: Module,
        dec: ast.Call,
        aliases: Dict[str, str],
        fn: ast.FunctionDef,
        findings: List[Finding],
    ) -> Optional[Set[str]]:
        """Static-arg names when `dec` is a jit-wrapping decorator call
        (`@partial(jax.jit, ...)` or `@jax.jit(...)`), else None."""
        origin = resolve_call(dec, aliases)
        if origin in _PARTIAL_ORIGINS:
            if not (dec.args and _is_jit_target(dec.args[0], aliases)):
                return None
        elif not _is_jit_target(dec.func, aliases):
            return None
        return self._statics_for(m, dec, fn, findings)

    def _statics_for(
        self,
        m: Module,
        call: ast.Call,
        fn: ast.FunctionDef,
        findings: List[Finding],
    ) -> Set[str]:
        """Resolve a jit call's static spec against fn's signature,
        emitting JAX005 for mismatches."""
        params = [a.arg for a in fn.args.args]
        nums, names = _static_spec(call)
        static: Set[str] = set()
        if nums is not None:
            for i in nums:
                if 0 <= i < len(params):
                    static.add(params[i])
                else:
                    findings.append(
                        Finding(
                            code="JAX005",
                            path=m.rel,
                            line=call.lineno,
                            message=(
                                f"static_argnums index {i} out of range "
                                f"for {fn.name}() with {len(params)} "
                                "positional parameters"
                            ),
                        )
                    )
        if names is not None:
            for nm in names:
                if nm in params:
                    static.add(nm)
                else:
                    findings.append(
                        Finding(
                            code="JAX005",
                            path=m.rel,
                            line=call.lineno,
                            message=(
                                f"static_argnames {nm!r} is not a "
                                f"parameter of {fn.name}() "
                                f"(params: {', '.join(params) or 'none'})"
                            ),
                        )
                    )
        return static

    # -- body checks -------------------------------------------------------

    @staticmethod
    def _module_globals(tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(stmt.target, ast.Name):
                    out.add(stmt.target.id)
        return out

    def _check_body(
        self,
        m: Module,
        aliases: Dict[str, str],
        body: _TracedBody,
        module_globals: Set[str],
        findings: List[Finding],
    ) -> None:
        fn = body.fn
        traced_params = {
            a.arg for a in fn.args.args
        } - body.static_names - {"self"}
        local_names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                local_names.add(node.id)

        def emit(code: str, node: ast.AST, msg: str) -> None:
            findings.append(
                Finding(
                    code=code,
                    path=m.rel,
                    line=getattr(node, "lineno", fn.lineno),
                    message=f"{msg} in traced body of {fn.name}()",
                )
            )

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                emit("JAX001", node, "`global` statement")
            elif isinstance(node, ast.Call):
                origin = resolve_call(node, aliases)
                if origin == "print":
                    emit("JAX001", node, "print() side effect")
                elif origin is not None and origin.split(".")[0] == "numpy":
                    emit(
                        "JAX002",
                        node,
                        f"host numpy call {origin}()",
                    )
                elif origin is not None and (
                    origin.startswith("time.")
                    or origin.startswith("random.")
                ):
                    emit(
                        "JAX006",
                        node,
                        f"host wall-clock/RNG call {origin}()",
                    )
                elif origin in ("int", "float", "bool"):
                    if (
                        node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in traced_params
                    ):
                        emit(
                            "JAX003",
                            node,
                            f"{origin}() coercion of traced parameter "
                            f"{node.args[0].id!r}",
                        )
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    emit("JAX003", node, ".item() device read")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        base = base.value
                    if (
                        isinstance(base, ast.Name)
                        and base is not t  # only subscript/attr stores
                        and base.id in module_globals
                        and base.id not in local_names
                    ):
                        emit(
                            "JAX004",
                            node,
                            f"mutation of module global {base.id!r}",
                        )
