"""API-invariant pass: registries that must stay in sync across layers.

Rules (codes):

* API001 — a stats emission (`*.stats.count("name", ...)` etc.) whose
  literal name is not declared in `utils/stats.py` `STAT_NAMES` (or
  covered by a `STAT_PREFIXES` prefix for dynamically-built families).
  Dashboards reference declared names; an undeclared emission is a
  metric nothing can find.
* API002 — a declared STAT_NAMES entry that no module emits: stale
  registry (dynamically-prefixed families are exempt — their full names
  never appear as literals).
* API003 — a config knob (dataclass field in `cli/config.py`) whose
  kebab-case name is missing from `docs/configuration.md`.
* API004 — a `server` CLI flag in `cli/main.py` that maps to no config
  knob (flags are overrides of config; an unmapped flag silently does
  nothing).
* API005 — a config knob with no corresponding `server` CLI flag
  (every knob must be settable from the command line, per the
  config-precedence contract flags > env > file > defaults).
* API006 — a span started (`start_span` / `start_span_from_headers` /
  `record_span`) with a literal name not declared in `utils/tracing.py`
  `SPAN_NAMES`. The flight recorder's assembly, dashboards, and the
  slow-query log key on these names; an undeclared span is a stage
  nothing can attribute.
* API007 — a declared SPAN_NAMES entry no module starts: stale
  registry (same contract as API002 for STAT_NAMES).
* API009 — a config knob no module ever reads at runtime: the field
  name never appears as an attribute read outside `cli/config.py`
  itself (flag tables and argparse strings don't count — only a real
  `cfg.section.knob` access does). A knob that parses, documents, and
  round-trips but influences nothing is dead configuration.

All facts are extracted statically from the ASTs — the pass never
imports the package, so it works on broken/half-edited trees too.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pilosa_tpu.analysis.framework import (
    Finding,
    Module,
    Pass,
    dotted_name,
)

__all__ = ["ApiInvariantsPass"]

_EMIT_METHODS = {"count", "gauge", "histogram", "timing", "set_value", "timer"}

# span-starting callables (methods on a tracer, or the module-level
# helpers in utils/tracing.py that route to the active trace's tracer)
_SPAN_METHODS = {"start_span", "start_span_from_headers", "record_span"}

# server flags that intentionally do NOT map to config knobs
_NON_KNOB_FLAGS = {
    "config",  # selects the TOML file the knobs come from
    "join",  # one-shot boot action, not persistent configuration
    "help",
}

# Config dataclass -> TOML/doc section name ("" = top-level)
_SECTION_CLASSES = {
    "Config": "",
    "ClusterConfig": "cluster",
    "SchedConfig": "sched",
    "TenantsConfig": "tenants",
    "HbmConfig": "hbm",
    "BsiConfig": "bsi",
    "IngestConfig": "ingest",
    "WalConfig": "wal",
    "MeshConfig": "mesh",
    "CacheConfig": "cache",
    "ResizeConfig": "resize",
    "TierConfig": "tier",
    "CoherenceConfig": "coherence",
    "AntiEntropyConfig": "anti_entropy",
    "MetricConfig": "metric",
    "TracingConfig": "tracing",
    "TelemetryConfig": "telemetry",
    "TLSConfig": "tls",
}


def _stats_receiver(call: ast.Call) -> bool:
    """True when the call target reads like a StatsClient emission:
    `stats.count(...)`, `self.stats.timing(...)`,
    `self.server.stats.count(...)`, or the inline labeled-family form
    `self.stats.with_tags("index:a").gauge(...)` (the child client is
    ephemeral — the emission still must name a declared stat)."""
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _EMIT_METHODS:
        return False
    recv = fn.value
    if (
        isinstance(recv, ast.Call)
        and isinstance(recv.func, ast.Attribute)
        and recv.func.attr == "with_tags"
    ):
        recv = recv.func.value
    name = dotted_name(recv)
    return name is not None and name.split(".")[-1] == "stats"


class ApiInvariantsPass(Pass):
    name = "api-invariants"
    rules = (
        "API001", "API002", "API003", "API004", "API005", "API006",
        "API007", "API008", "API009",
    )

    def __init__(self, docs_path: Optional[str] = None):
        # resolved lazily against the module set's repo root when None
        self._docs_path = docs_path

    def run(self, modules: Sequence[Module]) -> List[Finding]:
        findings: List[Finding] = []
        by_rel = {m.rel: m for m in modules}
        stats_mod = by_rel.get("pilosa_tpu/utils/stats.py")
        tracing_mod = by_rel.get("pilosa_tpu/utils/tracing.py")
        config_mod = by_rel.get("pilosa_tpu/cli/config.py")
        main_mod = by_rel.get("pilosa_tpu/cli/main.py")
        if stats_mod is not None:
            self._check_stats(modules, stats_mod, findings)
        if tracing_mod is not None:
            self._check_spans(modules, tracing_mod, findings)
        if config_mod is not None:
            knobs = self._config_knobs(config_mod)
            self._check_docs(config_mod, knobs, findings)
            if main_mod is not None:
                self._check_flags(main_mod, knobs, findings)
            self._check_knob_reads(modules, config_mod, knobs, findings)
        return findings

    # -- stats registry ----------------------------------------------------

    def _declared(
        self, stats_mod: Module
    ) -> Tuple[Set[str], Set[str], int, int]:
        names: Set[str] = set()
        prefixes: Set[str] = set()
        names_line = prefixes_line = 1
        for stmt in stats_mod.tree.body:
            if not (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                continue
            target = stmt.targets[0].id
            if target not in ("STAT_NAMES", "STAT_PREFIXES"):
                continue
            for node in ast.walk(stmt.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    if target == "STAT_NAMES":
                        names.add(node.value)
                    else:
                        prefixes.add(node.value)
            if target == "STAT_NAMES":
                names_line = stmt.lineno
            else:
                prefixes_line = stmt.lineno
        return names, prefixes, names_line, prefixes_line

    def _check_stats(
        self,
        modules: Sequence[Module],
        stats_mod: Module,
        findings: List[Finding],
    ) -> None:
        names, prefixes, names_line, _ = self._declared(stats_mod)
        emitted: Set[str] = set()
        for m in modules:
            if m.rel == stats_mod.rel:
                continue  # the client plumbing itself, not emissions
            for node in ast.walk(m.tree):
                if not (
                    isinstance(node, ast.Call) and _stats_receiver(node)
                ):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    emitted.add(arg.value)
                    if arg.value not in names and not any(
                        arg.value.startswith(p) for p in prefixes
                    ):
                        findings.append(
                            Finding(
                                code="API001",
                                path=m.rel,
                                line=node.lineno,
                                message=(
                                    f"stat {arg.value!r} emitted but not "
                                    "declared in utils/stats.py "
                                    "STAT_NAMES"
                                ),
                            )
                        )
                elif isinstance(arg, ast.JoinedStr):
                    # dynamic name: its literal leading part must sit
                    # under a declared prefix
                    lead = ""
                    if arg.values and isinstance(
                        arg.values[0], ast.Constant
                    ):
                        lead = str(arg.values[0].value)
                    if not any(lead.startswith(p) for p in prefixes):
                        findings.append(
                            Finding(
                                code="API001",
                                path=m.rel,
                                line=node.lineno,
                                message=(
                                    "dynamically-built stat name "
                                    f"(leading literal {lead!r}) not "
                                    "covered by utils/stats.py "
                                    "STAT_PREFIXES"
                                ),
                            )
                        )
        for name in sorted(names - emitted):
            findings.append(
                Finding(
                    code="API002",
                    path=stats_mod.rel,
                    line=names_line,
                    message=(
                        f"STAT_NAMES declares {name!r} but no module "
                        "emits it — stale registry entry"
                    ),
                )
            )
        self._check_labels(stats_mod, names, prefixes, findings)

    @staticmethod
    def _declared_labels(
        stats_mod: Module,
    ) -> Tuple[Dict[str, Tuple[str, ...]], int]:
        """Parse the STAT_LABELS literal: family name -> label-key tuple
        (tools/prom_lint.py loads the runtime dict; the gate checks the
        declaration itself stays coherent)."""
        labels: Dict[str, Tuple[str, ...]] = {}
        line = 1
        for stmt in stats_mod.tree.body:
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
                if isinstance(stmt, ast.AnnAssign) and stmt.value is not None
                else []
            )
            if not (
                len(targets) == 1
                and isinstance(targets[0], ast.Name)
                and targets[0].id == "STAT_LABELS"
                and isinstance(stmt.value, ast.Dict)
            ):
                continue
            line = stmt.lineno
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    continue
                keys = tuple(
                    e.value
                    for e in ast.walk(v)
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
                labels[k.value] = keys
        return labels, line

    def _check_labels(
        self,
        stats_mod: Module,
        names: Set[str],
        prefixes: Set[str],
        findings: List[Finding],
    ) -> None:
        """API008: every labeled family in STAT_LABELS must name a
        DECLARED stat with a non-empty label-key set — a typo'd family
        name would make prom_lint enforce labels on a series nobody
        emits while the real family renders unchecked."""
        labels, line = self._declared_labels(stats_mod)
        for family, keys in sorted(labels.items()):
            declared = family in names or any(
                family.startswith(p) for p in prefixes
            )
            if not declared:
                findings.append(
                    Finding(
                        code="API008",
                        path=stats_mod.rel,
                        line=line,
                        message=(
                            f"STAT_LABELS entry {family!r} is not a "
                            "declared stat (STAT_NAMES/STAT_PREFIXES) — "
                            "labeled-family rule would never match"
                        ),
                    )
                )
            if not keys:
                findings.append(
                    Finding(
                        code="API008",
                        path=stats_mod.rel,
                        line=line,
                        message=(
                            f"STAT_LABELS entry {family!r} declares no "
                            "label keys — an empty label set means the "
                            "family is unlabeled; remove the entry"
                        ),
                    )
                )

    # -- span-name registry ------------------------------------------------

    @staticmethod
    def _declared_spans(tracing_mod: Module) -> Tuple[Set[str], int]:
        names: Set[str] = set()
        line = 1
        for stmt in tracing_mod.tree.body:
            if not (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "SPAN_NAMES"
            ):
                continue
            for node in ast.walk(stmt.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    names.add(node.value)
            line = stmt.lineno
        return names, line

    def _check_spans(
        self,
        modules: Sequence[Module],
        tracing_mod: Module,
        findings: List[Finding],
    ) -> None:
        names, names_line = self._declared_spans(tracing_mod)
        started: Set[str] = set()
        for m in modules:
            if m.rel == tracing_mod.rel:
                continue  # the tracer plumbing itself, not start sites
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                # tracer.start_span("x") / tracing.record_span("x", ...)
                # method style, or a from-imported bare call
                if isinstance(fn, ast.Attribute):
                    if fn.attr not in _SPAN_METHODS:
                        continue
                elif isinstance(fn, ast.Name):
                    if fn.id not in _SPAN_METHODS:
                        continue
                else:
                    continue
                arg = node.args[0]
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    continue
                started.add(arg.value)
                if arg.value not in names:
                    findings.append(
                        Finding(
                            code="API006",
                            path=m.rel,
                            line=node.lineno,
                            message=(
                                f"span {arg.value!r} started but not "
                                "declared in utils/tracing.py SPAN_NAMES"
                            ),
                        )
                    )
        for name in sorted(names - started):
            findings.append(
                Finding(
                    code="API007",
                    path=tracing_mod.rel,
                    line=names_line,
                    message=(
                        f"SPAN_NAMES declares {name!r} but no module "
                        "starts it — stale registry entry"
                    ),
                )
            )

    # -- config knobs ------------------------------------------------------

    def _config_knobs(self, config_mod: Module) -> Dict[str, int]:
        """knob path ('bind', 'cluster.replicas', ...) -> decl line."""
        knobs: Dict[str, int] = {}
        section_class_names = set(_SECTION_CLASSES)
        for node in config_mod.tree.body:
            if not (
                isinstance(node, ast.ClassDef)
                and node.name in _SECTION_CLASSES
            ):
                continue
            section = _SECTION_CLASSES[node.name]
            for stmt in node.body:
                if not (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                ):
                    continue
                # skip sub-config aggregation fields on Config itself
                ann = stmt.annotation
                ann_name = (
                    ann.id
                    if isinstance(ann, ast.Name)
                    else dotted_name(ann) or ""
                )
                if ann_name in section_class_names:
                    continue
                field = stmt.target.id
                path = f"{section}.{field}" if section else field
                knobs[path] = stmt.lineno
        return knobs

    def _docs_text(self, config_mod: Module) -> Tuple[str, str]:
        if self._docs_path is not None:
            docs_path = self._docs_path
        else:
            root = os.path.dirname(
                os.path.dirname(os.path.dirname(config_mod.path))
            )
            docs_path = os.path.join(root, "docs", "configuration.md")
        try:
            with open(docs_path, encoding="utf-8") as fh:
                return fh.read(), docs_path
        except OSError:
            return "", docs_path

    def _check_docs(
        self,
        config_mod: Module,
        knobs: Dict[str, int],
        findings: List[Finding],
    ) -> None:
        text, _ = self._docs_text(config_mod)
        for path, line in sorted(knobs.items()):
            kebab = path.split(".")[-1].replace("_", "-")
            if kebab not in text:
                findings.append(
                    Finding(
                        code="API003",
                        path=config_mod.rel,
                        line=line,
                        message=(
                            f"config knob {path!r} ({kebab!r}) is not "
                            "documented in docs/configuration.md"
                        ),
                    )
                )

    def _check_knob_reads(
        self,
        modules: Sequence[Module],
        config_mod: Module,
        knobs: Dict[str, int],
        findings: List[Finding],
    ) -> None:
        """API009: a declared knob nothing ever reads. A knob counts as
        read only when its field name appears as an attribute access
        (`cfg.section.knob`, `self.knob`) in some module other than the
        config declarations themselves — flag tables, argparse strings
        and TOML keys are plumbing, not consumption."""
        read: Set[str] = set()
        for m in modules:
            if m.rel == config_mod.rel:
                continue
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Attribute):
                    read.add(node.attr)
        for path, line in sorted(knobs.items()):
            field = path.split(".")[-1]
            if field not in read:
                findings.append(
                    Finding(
                        code="API009",
                        path=config_mod.rel,
                        line=line,
                        message=(
                            f"config knob {path!r} is declared (and "
                            "documented, and flagged) but never read at "
                            "runtime — no module accesses `.{0}`; wire "
                            "it up or delete it".format(field)
                        ),
                    )
                )

    # -- CLI flags ---------------------------------------------------------

    @staticmethod
    def _server_flags(main_mod: Module) -> Dict[str, int]:
        """--flag-name (sans dashes, snake_cased) -> line, for the
        `server` subparser plus the top-level parser."""
        flags: Dict[str, int] = {}
        server_vars: Set[str] = set()
        parser_vars: Set[str] = set()
        for node in ast.walk(main_mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                callee = dotted_name(node.value.func) or ""
                if callee.endswith(".add_parser"):
                    args = node.value.args
                    if (
                        args
                        and isinstance(args[0], ast.Constant)
                        and args[0].value == "server"
                    ):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                server_vars.add(t.id)
                elif callee.endswith("ArgumentParser"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            parser_vars.add(t.id)
        for node in ast.walk(main_mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in (server_vars | parser_vars)
            ):
                continue
            for arg in node.args:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("--")
                ):
                    flags[arg.value[2:].replace("-", "_")] = node.lineno
        return flags

    def _check_flags(
        self,
        main_mod: Module,
        knobs: Dict[str, int],
        findings: List[Finding],
    ) -> None:
        flags = self._server_flags(main_mod)
        knob_matchers: Dict[str, str] = {}  # acceptable flag name -> knob
        for path in knobs:
            if "." in path:
                section, field = path.split(".", 1)
                knob_matchers[f"{section}_{field}"] = path
                knob_matchers.setdefault(field, path)
            else:
                knob_matchers[path] = path
        for flag, line in sorted(flags.items()):
            if flag in _NON_KNOB_FLAGS:
                continue
            if flag not in knob_matchers:
                findings.append(
                    Finding(
                        code="API004",
                        path=main_mod.rel,
                        line=line,
                        message=(
                            f"server flag --{flag.replace('_', '-')} "
                            "maps to no config knob in cli/config.py"
                        ),
                    )
                )
        matched_knobs = {
            knob_matchers[f] for f in flags if f in knob_matchers
        }
        for path, line in sorted(knobs.items()):
            if path not in matched_knobs:
                findings.append(
                    Finding(
                        code="API005",
                        path="pilosa_tpu/cli/config.py",
                        line=line,
                        message=(
                            f"config knob {path!r} has no `server` CLI "
                            "flag in cli/main.py"
                        ),
                    )
                )
