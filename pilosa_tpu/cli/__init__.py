"""CLI package (reference: /root/reference/cmd/ + ctl/)."""

from pilosa_tpu.cli.main import main  # noqa: F401
