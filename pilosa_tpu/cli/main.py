"""CLI: server / import / export / inspect / check / config subcommands.

Reference: /root/reference/cmd/ (cobra tree: root.go:28, server.go:60) and
ctl/ (ImportCommand csv pipeline ctl/import.go:82-392, ExportCommand
ctl/export.go:53, CheckCommand offline integrity ctl/check.go:47-133,
InspectCommand ctl/inspect.go:49, GenerateConfigCommand
ctl/generate_config.go:41). argparse instead of cobra/viper; same surface.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import urllib.request
from typing import List, Optional

from pilosa_tpu.cli.config import Config, parse_hosts


def _bool_flag(v: str) -> bool:
    """Explicit true/false flag value (for default-True knobs, where
    store_true could never express an override back to False). Anything
    unrecognized is a usage error — silently coercing a typo like
    'ture' to False would disable the knob with no diagnostic."""
    s = v.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(
        f"expected true/false, got {v!r}"
    )


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pilosa-tpu", description="TPU-native distributed bitmap index"
    )
    p.add_argument("--config", "-c", help="path to TOML config file")
    sub = p.add_subparsers(dest="command")

    sp = sub.add_parser("server", help="run a cluster node")
    sp.add_argument("--data-dir", "-d")
    sp.add_argument("--bind", "-b")
    sp.add_argument("--node-id")
    sp.add_argument("--log-path", help="append server log here (default stderr)")
    sp.add_argument(
        "--long-query-time", type=float,
        help="log queries slower than this many seconds (0 disables)",
    )
    sp.add_argument(
        "--max-writes-per-request", type=int,
        help="reject write batches larger than this",
    )
    sp.add_argument("--cluster-hosts", help="comma-separated id@uri entries")
    sp.add_argument("--replicas", type=int)
    sp.add_argument(
        "--coordinator", action="store_true", default=None,
        help="force this node to act as cluster coordinator",
    )
    sp.add_argument(
        "--probe-interval", type=float,
        help="coordinator liveness-probe ticker seconds (0 disables)",
    )
    sp.add_argument("--anti-entropy-interval", type=float)
    sp.add_argument(
        "--metric-service",
        help="metrics backend: none | expvar | prometheus | statsd",
    )
    sp.add_argument("--metric-host", help="statsd daemon host:port")
    sp.add_argument(
        "--metric-poll-interval", type=float,
        help="runtime-gauge sampling ticker seconds (0 disables)",
    )
    sp.add_argument(
        "--tracing-enabled", action="store_true", default=None,
        help="record spans for incoming queries",
    )
    sp.add_argument(
        "--tracing-sample-rate", type=float,
        help="fraction of queries traced when tracing is enabled",
    )
    sp.add_argument(
        "--tracing-ring", type=int,
        help="spans kept in the per-node flight-recorder ring "
        "(/debug/traces)",
    )
    sp.add_argument(
        "--telemetry-sample-interval", type=float,
        help="utilization-timeline sampler tick seconds (each tick also "
        "refreshes the residency gauges; 0 disables the sampler)",
    )
    sp.add_argument(
        "--telemetry-ring", type=int,
        help="utilization samples kept in the per-node /debug/timeline "
        "ring",
    )
    sp.add_argument(
        "--retry-max-attempts", type=int,
        help="internode RPC attempts within one deadline budget",
    )
    sp.add_argument(
        "--retry-base-backoff", type=float,
        help="seconds before the first internode retry (doubles per retry)",
    )
    sp.add_argument(
        "--breaker-threshold", type=int,
        help="consecutive failures before a peer's circuit opens",
    )
    sp.add_argument(
        "--breaker-cooldown", type=float,
        help="seconds a circuit stays open before a half-open probe",
    )
    sp.add_argument(
        "--query-deadline", type=float,
        help="wall-clock bound on one distributed query fan-out, seconds",
    )
    sp.add_argument(
        "--max-concurrent-queries", type=int,
        help="queries executing at once; extra queries queue (0 disables "
        "admission control)",
    )
    sp.add_argument(
        "--admission-queue-depth", type=int,
        help="waiting queries before load shedding replies 429",
    )
    sp.add_argument(
        "--admission-byte-budget", type=int,
        help="in-flight estimated device bytes before queries queue "
        "(0 = follow the HBM devcache budget)",
    )
    sp.add_argument(
        "--admission-default-class",
        choices=["interactive", "batch", "internal"],
        help="priority class for queries without an X-Pilosa-Priority "
        "header",
    )
    sp.add_argument(
        "--shed-retry-after", type=float,
        help="Retry-After seconds sent with 429 load-shed responses",
    )
    sp.add_argument(
        "--tenants-default-qps", type=float,
        help="per-index query-rate limit, queries/second (token bucket "
        "with a one-second burst; 0 disables)",
    )
    sp.add_argument(
        "--tenants-default-bytes-per-s", type=float,
        help="per-index device-byte rate limit priced by the admission "
        "cost estimator, bytes/second (0 disables)",
    )
    sp.add_argument(
        "--tenants-default-inflight-bytes", type=int,
        help="per-index cap on estimated device bytes in flight at once "
        "(0 disables)",
    )
    sp.add_argument(
        "--tenants-default-hbm-bytes", type=int,
        help="per-index HBM devcache residency quota; eviction pressure "
        "lands on over-quota indexes first (0 disables)",
    )
    sp.add_argument(
        "--tenants-default-cache-bytes", type=int,
        help="per-index result-cache byte quota (0 disables)",
    )
    sp.add_argument(
        "--tenants-overrides", nargs="*",
        help="per-index limit overrides, one entry per index: "
        "'idx:qps=5;bytes-per-s=1e6;hbm-bytes=65536' (semicolon-joined "
        "key=value pairs; keys: qps, bytes-per-s, inflight-bytes, "
        "hbm-bytes, cache-bytes)",
    )
    sp.add_argument(
        "--hbm-extent-rows", type=int,
        help="shards per HBM operand extent — the paging granularity "
        "under memory pressure (0 stages whole stacks monolithically)",
    )
    sp.add_argument(
        "--hbm-prefetch-depth", type=int,
        help="queued warm tasks the background extent prefetcher holds "
        "(0 disables prefetching)",
    )
    sp.add_argument(
        "--hbm-pin-timeout", type=float,
        help="seconds before a leaked extent pin is forcibly released "
        "(safety valve; 0 disables)",
    )
    sp.add_argument(
        "--bsi-slab-planes", type=int,
        help="magnitude planes per compiled dispatch for plane-streamed "
        "BSI aggregates (Sum/Min/Max/Range counts): peak plane "
        "residency stays slab-sized however deep the field "
        "(<= 0 restores the default)",
    )
    sp.add_argument(
        "--import-concurrency", type=int,
        help="parallel replica-import RPCs per bulk import call (shard "
        "batches ship to their owner nodes on a pool this wide)",
    )
    sp.add_argument(
        "--merge-device-threshold", type=int,
        help="staged positions per read-barrier burst at which the "
        "cross-fragment deferred-delta merge dispatches the device "
        "program instead of the vectorized host pass (<0 never, "
        "0 always; unset = backend auto — 65536 on an accelerator, "
        "never on the CPU backend)",
    )
    sp.add_argument(
        "--wal-sync-interval", type=float,
        help="WAL group-commit fsync cadence, seconds: 0 = strict (every "
        "commit group fsyncs before any caller returns), > 0 = bounded-"
        "loss mode (callers return after the buffered write; a "
        "background syncer fsyncs on this interval — the crash loss "
        "window)",
    )
    sp.add_argument(
        "--mesh-group",
        help="ICI domain id of this node: nodes sharing a non-empty group "
        "execute mesh-local queries as one compiled sharded program "
        "instead of per-node HTTP legs (empty disables)",
    )
    sp.add_argument(
        "--cache-result-mb", type=int,
        help="versioned result cache LRU byte budget in MB — repeat "
        "Count/TopN/GroupBy queries revalidate against fragment "
        "versions and serve from host memory with zero dispatches "
        "(0 disables)",
    )
    sp.add_argument(
        "--cache-count-repair", type=_bool_flag,
        help="patch cached Counts in place from the merge barrier's "
        "word deltas after set-only staged bursts instead of "
        "recomputing (true/false)",
    )
    sp.add_argument(
        "--mesh-min-nodes", type=int,
        help="group-local owner nodes a fan-out must span before the "
        "mesh-group fold engages (0 disables mesh-local execution)",
    )
    sp.add_argument(
        "--mesh-ici-gbps", type=float,
        help="assumed intra-group (ICI) collective bandwidth, GB/s, for "
        "admission's collective-cost terms",
    )
    sp.add_argument(
        "--mesh-dcn-gbps", type=float,
        help="assumed cross-group (HTTP/DCN) bandwidth, GB/s, for "
        "admission's collective-cost terms",
    )
    sp.add_argument(
        "--resize-transfer-concurrency", type=int,
        help="parallel fragment transfer legs per node during a "
        "streaming resize",
    )
    sp.add_argument(
        "--resize-cutover-timeout", type=float,
        help="wall-clock bound on a resize step's delta catch-up barrier, "
        "seconds",
    )
    sp.add_argument(
        "--resize-resume-policy", choices=["resume", "abort"],
        help="on a failed resize transfer leg: 'resume' retries once from "
        "the per-fragment transfer ledger, 'abort' rolls the job back "
        "immediately",
    )
    sp.add_argument(
        "--tier-store-path",
        help="shared object-store directory for tiered storage — idle "
        "fragments demote to immutable snapshot objects there and "
        "hydrate on demand (empty disables the tier plane)",
    )
    sp.add_argument(
        "--tier-placement", choices=["hot", "warm", "cold"],
        help="default fragment placement: hot (host + device), warm "
        "(host only, device residency shed when idle), cold (demoted "
        "to the object store when idle)",
    )
    sp.add_argument(
        "--tier-overrides", nargs="*",
        help="per-index placement overrides, one entry per index: "
        "'idx:placement=cold'",
    )
    sp.add_argument(
        "--tier-demote-after", type=float,
        help="idle seconds before a cold-placement fragment demotes to "
        "the object store",
    )
    sp.add_argument(
        "--tier-host-budget-bytes", type=int,
        help="local snapshot+WAL byte budget; beyond it the tier ticker "
        "demotes least-recently-used fragments regardless of idle time "
        "(0 = unlimited)",
    )
    sp.add_argument(
        "--tier-fetch-concurrency", type=int,
        help="concurrent object-store transfers per node (demote "
        "uploads + hydration fetches share the bound)",
    )
    sp.add_argument(
        "--coherence-lease-duration", type=float,
        help="coherence lease bound, seconds: peers holding a lease serve "
        "fan-out warm hits from pushed version mirrors with zero "
        "version RTTs; on publisher death/partition staleness is "
        "bounded by this window before falling back to revalidation "
        "(0 disables leases)",
    )
    sp.add_argument(
        "--coherence-publish-batch-ms", type=float,
        help="invalidation publish batching window, milliseconds — "
        "version-vector bumps funnel through merge-barrier/stage-bulk "
        "and ship to lease holders at this cadence",
    )
    sp.add_argument(
        "--coherence-max-subscriptions", type=int,
        help="live query subscriptions per node; registration beyond the "
        "cap sheds with 429 (0 disables subscriptions)",
    )
    sp.add_argument(
        "--coherence-sub-poll-interval", type=float,
        help="fallback re-check cadence, seconds, for subscription "
        "results whose queries fall outside push invalidation coverage",
    )
    sp.add_argument(
        "--join",
        help="coordinator URI to join on boot (self-registers and waits for "
        "the resize job; the listenForJoins role, cluster.go:1141)",
    )
    sp.add_argument("--verbose", action="store_true", default=None)
    sp.add_argument("--tls-certificate", help="PEM cert chain; serve HTTPS")
    sp.add_argument("--tls-key", help="PEM private key for --tls-certificate")
    sp.add_argument(
        "--tls-skip-verify",
        action="store_true",
        default=None,
        help="internode client trusts any peer certificate (self-signed)",
    )
    sp.add_argument(
        "--tls-ca-certificate",
        help="internode client verifies peers against this CA bundle",
    )

    ip = sub.add_parser("import", help="bulk-import CSV rows (row,col[,ts])")
    ip.add_argument("--host", default="http://localhost:10101")
    ip.add_argument("--index", "-i", required=True)
    ip.add_argument("--field", "-f", required=True)
    ip.add_argument("--batch-size", type=int, default=100_000)
    ip.add_argument("--clear", action="store_true")
    ip.add_argument("--create", action="store_true", help="create index/field")
    ip.add_argument("--field-type", default="set")
    ip.add_argument("--field-keys", action="store_true")
    ip.add_argument("--index-keys", action="store_true")
    ip.add_argument("paths", nargs="*", help="CSV files ('-' or empty = stdin)")

    ep = sub.add_parser("export", help="export a field as CSV")
    ep.add_argument("--host", default="http://localhost:10101")
    ep.add_argument("--index", "-i", required=True)
    ep.add_argument("--field", "-f", required=True)
    ep.add_argument("--output", "-o", help="output path (default stdout)")

    np_ = sub.add_parser("inspect", help="dump fragment info from a data dir")
    np_.add_argument("data_dir")
    np_.add_argument("--index")
    np_.add_argument("--field")

    cp = sub.add_parser("check", help="offline integrity check of data files")
    cp.add_argument("paths", nargs="+", help=".snap / .wal files or data dirs")

    sub.add_parser("config", help="print the effective configuration")
    sub.add_parser("generate-config", help="print default configuration")
    return p


# argparse dest -> (section, knob) for every server flag that overrides a
# Config field; None section means a flat Config field. The api-invariants
# pass checks this stays in sync with cli/config.py's dataclasses.
_FLAG_KNOBS = {
    "data_dir": (None, "data_dir"),
    "bind": (None, "bind"),
    "node_id": (None, "node_id"),
    "log_path": (None, "log_path"),
    "verbose": (None, "verbose"),
    "long_query_time": (None, "long_query_time"),
    "max_writes_per_request": (None, "max_writes_per_request"),
    "import_concurrency": (None, "import_concurrency"),
    "cluster_hosts": ("cluster", "hosts"),
    "replicas": ("cluster", "replicas"),
    "coordinator": ("cluster", "coordinator"),
    "probe_interval": ("cluster", "probe_interval"),
    "retry_max_attempts": ("cluster", "retry_max_attempts"),
    "retry_base_backoff": ("cluster", "retry_base_backoff"),
    "breaker_threshold": ("cluster", "breaker_threshold"),
    "breaker_cooldown": ("cluster", "breaker_cooldown"),
    "query_deadline": ("cluster", "query_deadline"),
    "max_concurrent_queries": ("sched", "max_concurrent_queries"),
    "admission_queue_depth": ("sched", "admission_queue_depth"),
    "admission_byte_budget": ("sched", "admission_byte_budget"),
    "admission_default_class": ("sched", "admission_default_class"),
    "shed_retry_after": ("sched", "shed_retry_after"),
    "tenants_default_qps": ("tenants", "default_qps"),
    "tenants_default_bytes_per_s": ("tenants", "default_bytes_per_s"),
    "tenants_default_inflight_bytes": ("tenants", "default_inflight_bytes"),
    "tenants_default_hbm_bytes": ("tenants", "default_hbm_bytes"),
    "tenants_default_cache_bytes": ("tenants", "default_cache_bytes"),
    "tenants_overrides": ("tenants", "overrides"),
    "hbm_extent_rows": ("hbm", "extent_rows"),
    "hbm_prefetch_depth": ("hbm", "prefetch_depth"),
    "hbm_pin_timeout": ("hbm", "pin_timeout"),
    "bsi_slab_planes": ("bsi", "slab_planes"),
    "merge_device_threshold": ("ingest", "merge_device_threshold"),
    "wal_sync_interval": ("wal", "sync_interval"),
    "mesh_group": ("mesh", "group"),
    "mesh_min_nodes": ("mesh", "min_nodes"),
    "cache_result_mb": ("cache", "result_mb"),
    "cache_count_repair": ("cache", "count_repair"),
    "mesh_ici_gbps": ("mesh", "ici_gbps"),
    "mesh_dcn_gbps": ("mesh", "dcn_gbps"),
    "resize_transfer_concurrency": ("resize", "transfer_concurrency"),
    "resize_cutover_timeout": ("resize", "cutover_timeout"),
    "resize_resume_policy": ("resize", "resume_policy"),
    "tier_store_path": ("tier", "store_path"),
    "tier_placement": ("tier", "placement"),
    "tier_overrides": ("tier", "overrides"),
    "tier_demote_after": ("tier", "demote_after"),
    "tier_host_budget_bytes": ("tier", "host_budget_bytes"),
    "tier_fetch_concurrency": ("tier", "fetch_concurrency"),
    "coherence_lease_duration": ("coherence", "lease_duration"),
    "coherence_publish_batch_ms": ("coherence", "publish_batch_ms"),
    "coherence_max_subscriptions": ("coherence", "max_subscriptions"),
    "coherence_sub_poll_interval": ("coherence", "sub_poll_interval"),
    "anti_entropy_interval": ("anti_entropy", "interval"),
    "metric_service": ("metric", "service"),
    "metric_host": ("metric", "host"),
    "metric_poll_interval": ("metric", "poll_interval"),
    "tracing_enabled": ("tracing", "enabled"),
    "tracing_sample_rate": ("tracing", "sample_rate"),
    "tracing_ring": ("tracing", "ring"),
    "telemetry_sample_interval": ("telemetry", "sample_interval"),
    "telemetry_ring": ("telemetry", "ring"),
    "tls_certificate": ("tls", "certificate"),
    "tls_key": ("tls", "key"),
    "tls_skip_verify": ("tls", "skip_verify"),
    "tls_ca_certificate": ("tls", "ca_certificate"),
}


def _load_config(args) -> Config:
    overrides: dict = {}
    for dest, (section, knob) in _FLAG_KNOBS.items():
        v = getattr(args, dest, None)
        if v is None:
            continue
        if section is None:
            overrides[knob] = v
        else:
            overrides.setdefault(section, {})[knob] = v
    return Config.load(path=args.config, overrides=overrides)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def _scheme(cfg: Config) -> str:
    """URI scheme this node serves on (TLS flips the whole plane to https,
    including the id derivation from --cluster-hosts entries)."""
    return "https" if cfg.tls.certificate else "http"


def _join_on_boot(
    srv,
    coordinator_uri: str,
    timeout: float = 180.0,
    clock=None,
    wake=None,
) -> None:
    """Self-register with the coordinator and wait until this node is an
    active member (reference: gossip join -> listenForJoins -> resize job,
    cluster.go:1141,1796). Retries while the coordinator is busy with
    another resize — concurrent joins serialize on the coordinator's
    one-job-at-a-time rule.

    `clock` (monotonic-seconds callable) and `wake` (Event-like; `.wait(t)`
    bounds each poll step and an external `.set()` wakes the loop
    immediately) are injectable so tests drive the loop on a virtual clock
    instead of racing wall-time sleeps."""
    import threading
    import time

    from pilosa_tpu.server.client import ClientError

    if clock is None:
        clock = time.monotonic
    if wake is None:
        wake = threading.Event()
    payload = {
        "id": srv.node.id,
        "uri": srv.node.uri,
        # the joiner's ICI-domain declaration rides the join so the
        # post-resize topology carries its mesh-group membership
        "meshGroup": srv.mesh_group_name,
    }
    deadline = clock() + timeout
    registered_at: Optional[float] = None
    while clock() < deadline:
        if registered_at is None:
            try:
                srv.client.join_cluster(coordinator_uri, payload)
                registered_at = clock()
            except ClientError as e:
                # coordinator busy (a resize job is already running) or not
                # up yet: back off and retry
                print(f"join: waiting for coordinator: {e}", file=sys.stderr)
                wake.wait(1.0)
                continue
        elif len(srv.cluster.nodes) <= 1 and clock() - registered_at > 10.0:
            # the join resize aborted and rolled us back to a solo
            # cluster: re-register rather than idling out the deadline
            print("join: resize rolled back; re-registering", file=sys.stderr)
            registered_at = None
            continue
        if (
            len(srv.cluster.nodes) > 1
            and any(n.id == srv.node.id for n in srv.cluster.nodes)
            and srv.state == "NORMAL"
        ):
            print(
                f"joined cluster of {len(srv.cluster.nodes)} nodes via "
                f"{coordinator_uri}",
                file=sys.stderr,
            )
            return
        wake.wait(0.2)
    raise SystemExit(f"join via {coordinator_uri} did not complete in {timeout}s")


def cmd_server(cfg: Config, wait: bool = True, join: Optional[str] = None):
    from pilosa_tpu.cluster.topology import Node
    from pilosa_tpu.server.node import NodeServer

    data_dir = os.path.expanduser(cfg.data_dir) if cfg.data_dir else None
    hosts = parse_hosts(cfg.cluster.hosts, default_scheme=_scheme(cfg))
    node_id = cfg.node_id
    if not node_id:
        # derive the same id parse_hosts would give this bind address, so a
        # '--cluster-hosts host:port,...' entry naming us matches our id
        my_uri = cfg.bind if cfg.bind.startswith("http") else f"{_scheme(cfg)}://{cfg.bind}"
        matched = [nid for nid, uri in hosts if uri == my_uri]
        node_id = matched[0] if matched else cfg.bind.replace(":", "-")
    from pilosa_tpu.utils.logger import new_logger

    log_stream = open(cfg.log_path, "a") if cfg.log_path else None
    srv = NodeServer(
        data_dir,
        node_id,
        bind=cfg.bind,
        replica_n=cfg.cluster.replicas,
        anti_entropy_interval=cfg.anti_entropy.interval,
        probe_interval=cfg.cluster.probe_interval,
        retry_max_attempts=cfg.cluster.retry_max_attempts,
        retry_base_backoff=cfg.cluster.retry_base_backoff,
        breaker_threshold=cfg.cluster.breaker_threshold,
        breaker_cooldown=cfg.cluster.breaker_cooldown,
        query_deadline=cfg.cluster.query_deadline,
        max_concurrent_queries=cfg.sched.max_concurrent_queries,
        admission_queue_depth=cfg.sched.admission_queue_depth,
        admission_byte_budget=cfg.sched.admission_byte_budget,
        admission_default_class=cfg.sched.admission_default_class,
        shed_retry_after=cfg.sched.shed_retry_after,
        tenant_default_qps=cfg.tenants.default_qps,
        tenant_default_bytes_per_s=cfg.tenants.default_bytes_per_s,
        tenant_default_inflight_bytes=cfg.tenants.default_inflight_bytes,
        tenant_default_hbm_bytes=cfg.tenants.default_hbm_bytes,
        tenant_default_cache_bytes=cfg.tenants.default_cache_bytes,
        tenant_overrides=cfg.tenants.overrides,
        hbm_extent_rows=cfg.hbm.extent_rows,
        hbm_prefetch_depth=cfg.hbm.prefetch_depth,
        hbm_pin_timeout=cfg.hbm.pin_timeout,
        bsi_slab_planes=cfg.bsi.slab_planes,
        merge_device_threshold=cfg.ingest.merge_device_threshold,
        wal_sync_interval=cfg.wal.sync_interval,
        mesh_group=cfg.mesh.group,
        mesh_min_nodes=cfg.mesh.min_nodes,
        mesh_ici_gbps=cfg.mesh.ici_gbps,
        mesh_dcn_gbps=cfg.mesh.dcn_gbps,
        cache_result_mb=cfg.cache.result_mb,
        cache_count_repair=cfg.cache.count_repair,
        import_concurrency=cfg.import_concurrency,
        max_writes_per_request=cfg.max_writes_per_request,
        resize_transfer_concurrency=cfg.resize.transfer_concurrency,
        resize_cutover_timeout=cfg.resize.cutover_timeout,
        resize_resume_policy=cfg.resize.resume_policy,
        tier_store_path=os.path.expanduser(cfg.tier.store_path) if cfg.tier.store_path else "",
        tier_placement=cfg.tier.placement,
        tier_overrides=cfg.tier.overrides,
        tier_demote_after=cfg.tier.demote_after,
        tier_host_budget_bytes=cfg.tier.host_budget_bytes,
        tier_fetch_concurrency=cfg.tier.fetch_concurrency,
        coherence_lease_duration=cfg.coherence.lease_duration,
        coherence_publish_batch_ms=cfg.coherence.publish_batch_ms,
        coherence_max_subscriptions=cfg.coherence.max_subscriptions,
        coherence_sub_poll_interval=cfg.coherence.sub_poll_interval,
        stats_service=cfg.metric.service,
        stats_host=cfg.metric.host,
        metric_poll_interval=cfg.metric.poll_interval,
        tracing_enabled=cfg.tracing.enabled,
        trace_sample_rate=cfg.tracing.sample_rate,
        trace_ring=cfg.tracing.ring,
        telemetry_sample_interval=cfg.telemetry.sample_interval,
        telemetry_ring=cfg.telemetry.ring,
        long_query_time=cfg.long_query_time,
        logger=new_logger(verbose=cfg.verbose, stream=log_stream),
        tls_cert=os.path.expanduser(cfg.tls.certificate) if cfg.tls.certificate else "",
        tls_key=os.path.expanduser(cfg.tls.key) if cfg.tls.key else "",
        tls_skip_verify=cfg.tls.skip_verify,
        tls_ca_cert=os.path.expanduser(cfg.tls.ca_certificate) if cfg.tls.ca_certificate else "",
    )
    srv.start()
    # static --cluster-hosts flags SEED a cluster; once membership is on
    # disk (.topology, written whenever a multi-node topology installs),
    # disk wins on reboot (cluster.go:1657-1692) — otherwise a restart
    # would silently revert a resized cluster to its stale launch config
    # and strand the re-placed fragments. Flags still HEAL peer URIs: the
    # membership (ids/coordinator/replicaN) comes from disk, but an
    # operator who moved a peer to a new address updates it via flags
    # (the reference re-learns URIs through gossip; static flags are our
    # address channel).
    if srv.topology_restored:
        if hosts:
            healed = srv.heal_peer_uris(hosts)
            print(
                "cluster-hosts: membership restored from .topology"
                + (f"; healed URIs for {healed}" if healed else ""),
                file=sys.stderr,
            )
    elif hosts:
        my_uri = cfg.bind if cfg.bind.startswith("http") else f"{_scheme(cfg)}://{cfg.bind}"
        members = []
        for nid, uri in hosts:
            if uri == my_uri and nid != srv.node.id:
                # the entry naming THIS address keeps the durable .id —
                # two members with one URI would give placement a phantom
                # owner no server identifies as
                print(
                    f"cluster-hosts id {nid!r} for this address overridden "
                    f"by on-disk .id {srv.node.id!r}",
                    file=sys.stderr,
                )
                nid = srv.node.id
            members.append(Node(id=nid, uri=uri))
        if not any(m.id == srv.node.id for m in members):
            members.append(Node(id=srv.node.id, uri=srv.node.uri))
        members[0].is_coordinator = True
        srv.set_topology(members, replica_n=cfg.cluster.replicas)
    if join:
        if srv.topology_restored:
            print(
                f"--join {join} ignored: membership restored from .topology "
                f"(remove {srv._topology_path} to join a different cluster)",
                file=sys.stderr,
            )
        else:
            _join_on_boot(srv, join)
    print(
        f"pilosa-tpu node {srv.node.id} listening on {srv.node.uri}",
        file=sys.stderr,
    )
    if wait:
        stop = []
        signal.signal(signal.SIGINT, lambda *a: stop.append(1))
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        try:
            while not stop:
                signal.pause()
        finally:
            srv.stop()
    return srv


def _iter_csv_rows(paths: List[str]):
    files = paths or ["-"]
    for path in files:
        fh = sys.stdin if path == "-" else open(path)
        try:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(",")
                if len(parts) < 2:
                    raise ValueError(f"bad csv line: {line!r}")
                yield parts[0], parts[1], (parts[2] if len(parts) > 2 else None)
        finally:
            if path != "-":
                fh.close()


def _post_json(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def cmd_import(args) -> int:
    def maybe_int(s):
        try:
            return int(s)
        except ValueError:
            return s  # string key

    if args.create:
        _post_json(
            f"{args.host}/index/{args.index}",
            {"options": {"keys": args.index_keys}},
        )
        _post_json(
            f"{args.host}/index/{args.index}/field/{args.field}",
            {"options": {"type": args.field_type, "keys": args.field_keys}},
        )
    batch_rows, batch_cols, batch_ts, n = [], [], [], 0
    is_value = args.field_type == "int"

    def flush():
        nonlocal batch_rows, batch_cols, batch_ts
        if not batch_cols:
            return
        if is_value:
            _post_json(
                f"{args.host}/index/{args.index}/field/{args.field}/import-value",
                {"cols": batch_cols, "values": [int(r) for r in batch_rows]},
            )
        else:
            body = {"rows": batch_rows, "cols": batch_cols}
            if any(t is not None for t in batch_ts):
                body["timestamps"] = batch_ts
            if args.clear:
                body["clear"] = True
            _post_json(
                f"{args.host}/index/{args.index}/field/{args.field}/import", body
            )
        batch_rows, batch_cols, batch_ts = [], [], []

    for row, col, ts in _iter_csv_rows(args.paths):
        batch_rows.append(maybe_int(row))
        batch_cols.append(maybe_int(col))
        batch_ts.append(ts)
        n += 1
        if len(batch_cols) >= args.batch_size:
            flush()
    flush()
    print(f"imported {n} records", file=sys.stderr)
    return 0


def cmd_export(args) -> int:
    url = f"{args.host}/export?index={args.index}&field={args.field}"
    with urllib.request.urlopen(url, timeout=120) as resp:
        data = resp.read()
    if args.output:
        with open(args.output, "wb") as f:
            f.write(data)
    else:
        sys.stdout.write(data.decode())
    return 0


def cmd_inspect(args) -> int:
    from pilosa_tpu.core.holder import Holder

    h = Holder(args.data_dir).open()
    try:
        for idx in h.indexes():
            if args.index and idx.name != args.index:
                continue
            for f in idx.fields(include_hidden=True):
                if args.field and f.name != args.field:
                    continue
                for vname, v in f.views.items():
                    for shard in sorted(v.fragments):
                        frag = v.fragments[shard]
                        rows, _ = frag.pairs()
                        n_rows = len(frag.row_ids())
                        print(
                            f"{idx.name}/{f.name}/{vname}/shard={shard}: "
                            f"rows={n_rows} bits={len(rows)} op_n={frag._op_n}"
                        )
    finally:
        h.close()
    return 0


def cmd_check(paths: List[str]) -> int:
    """Offline integrity check (reference: ctl/check.go:47-133)."""
    from pilosa_tpu.core import wal as walmod

    failed = 0
    todo: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                todo.extend(
                    os.path.join(root, fn)
                    for fn in files
                    if fn.endswith((".snap", ".wal", ".bitmap", ".roaring"))
                )
        else:
            todo.append(p)
    for p in todo:
        try:
            if p.endswith(".snap"):
                shard, n_bits, rows = walmod.read_snapshot(p)
                total = sum(rb.count() for rb in rows.values())
                print(f"{p}: ok shard={shard} rows={len(rows)} bits={total}")
            elif p.endswith(".wal"):
                n_ops, status, detail = walmod.check_wal(p)
                if status == "corrupt":
                    raise ValueError(f"{detail} (after {n_ops} valid ops)")
                note = f" ({detail}, discarded on replay)" if status == "torn" else ""
                print(f"{p}: ok ops={n_ops}{note}")
            elif p.endswith((".bitmap", ".roaring")):
                # reference-format roaring files (ctl/check.go checks .bitmap)
                from pilosa_tpu.core import roaring_io

                with open(p, "rb") as fh:
                    info = roaring_io.inspect(fh.read())
                print(
                    f"{p}: ok dialect={info['dialect']} bits={info['bit_count']} "
                    f"max={info['max_position']}"
                )
            else:
                print(f"{p}: skipped (unknown extension)")
        except Exception as e:
            print(f"{p}: CORRUPT: {e}")
            failed += 1
    return 1 if failed else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 2
    if args.command == "server":
        cmd_server(_load_config(args), join=getattr(args, "join", None))
        return 0
    if args.command == "import":
        return cmd_import(args)
    if args.command == "export":
        return cmd_export(args)
    if args.command == "inspect":
        return cmd_inspect(args)
    if args.command == "check":
        return cmd_check(args.paths)
    if args.command == "config":
        sys.stdout.write(_load_config(args).to_toml())
        return 0
    if args.command == "generate-config":
        sys.stdout.write(Config().to_toml())
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
