"""Config schema + TOML/env/flag merge.

Reference: /root/reference/server/config.go:48-157 (the TOML schema) and
cmd/root.go:94-131 setAllConfig — precedence flags > env (PILOSA_*) > TOML
file > defaults. Same precedence here with the PILOSA_TPU_ env prefix.
`pilosa-tpu config` dumps the effective TOML (ctl/config.go);
`generate-config` emits defaults (ctl/generate_config.go:41)."""

from __future__ import annotations

import dataclasses
import os

try:  # tomllib is stdlib only from 3.11; 3.10 environments carry tomli
    import tomllib
except ImportError:  # pragma: no cover - depends on interpreter version
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from typing import List, Optional

ENV_PREFIX = "PILOSA_TPU_"


@dataclass
class ClusterConfig:
    # static membership: list of "node_id@http://host:port" entries; empty
    # means single-node (reference: cluster.hosts + disabled)
    hosts: List[str] = field(default_factory=list)
    replicas: int = 1
    coordinator: bool = False
    # coordinator liveness-probe ticker, seconds; 0 disables (the SWIM
    # role — reference gossip probes continuously, gossip/gossip.go:364)
    probe_interval: float = 2.0
    # internode RPC fault tolerance (server/faults.py): attempts share
    # one deadline budget per request; per-peer circuit breakers fast-
    # fail requests to known-dead peers; query-deadline bounds a whole
    # distributed fan-out including failover re-map rounds
    retry_max_attempts: int = 3
    retry_base_backoff: float = 0.05  # seconds before the first retry
    breaker_threshold: int = 5  # consecutive failures before open
    breaker_cooldown: float = 2.0  # seconds open before a half-open probe
    query_deadline: float = 30.0  # seconds per distributed query


@dataclass
class SchedConfig:
    # query admission control & QoS (pilosa_tpu/sched/): every query is
    # admitted before it may dispatch — bounded concurrency, a bounded
    # deadline/priority-aware queue, 429 load shedding
    max_concurrent_queries: int = 16  # executing at once; 0 disables sched
    admission_queue_depth: int = 128  # waiting queries before shedding
    admission_byte_budget: int = 0  # in-flight device bytes; 0 = HBM budget
    admission_default_class: str = "interactive"  # headerless queries
    shed_retry_after: float = 1.0  # Retry-After seconds on 429


@dataclass
class TenantsConfig:
    # multi-tenant QoS enforcement (sched/tenants.py; docs/
    # configuration.md "[tenants]"): per-index token-bucket rate limits
    # and byte quotas, enforced at admission (429 + informed
    # Retry-After) and in both caches' eviction loops. 0 = unlimited.
    # Defaults apply to EVERY index; `overrides` entries of the form
    # "index:knob=value[;knob=value...]" (kebab knob names: qps,
    # bytes-per-s, inflight-bytes, hbm-bytes, cache-bytes) replace
    # individual defaults per index.
    default_qps: float = 0.0  # admitted queries/s per index
    default_bytes_per_s: float = 0.0  # estimated device bytes/s per index
    default_inflight_bytes: int = 0  # in-flight device-byte quota per index
    default_hbm_bytes: int = 0  # HBM residency quota per index
    default_cache_bytes: int = 0  # result-cache byte quota per index
    overrides: List[str] = field(default_factory=list)


@dataclass
class HbmConfig:
    # HBM residency manager (pilosa_tpu/hbm/): operand stacks page in
    # and out of the device budget as shard-major EXTENTS instead of
    # monolithic entries, so a budget below one query's working set
    # re-stages only evicted slices (docs/configuration.md "HBM
    # residency")
    extent_rows: int = 256  # shards per extent; 0 = monolithic staging
    prefetch_depth: int = 0  # warm-queue bound; 0 disables the prefetcher
    pin_timeout: float = 60.0  # stale-pin safety valve, seconds; 0 = off


@dataclass
class BsiConfig:
    # plane-streamed BSI aggregates (exec/bsistream.py; docs/
    # configuration.md "BSI aggregates"): Sum/Min/Max and single-
    # condition Range counts stage and reduce magnitude planes in slabs
    # of this many planes per compiled dispatch — peak plane residency
    # is slab-sized however deep the field, and a field at or under the
    # slab answers in ONE dispatch. <= 0 restores the default (16).
    slab_planes: int = 16


@dataclass
class IngestConfig:
    # bulk-ingest merge barrier (core/merge.py; docs/configuration.md
    # "Ingest"): staged deltas merge cross-fragment-batched at read
    # barriers — one device program launch per burst at or above the
    # threshold, one vectorized host pass below it. None = AUTO
    # (65536 on a real accelerator, device-off on the CPU backend,
    # where the XLA sort is the same silicon ~6x slower than numpy's)
    merge_device_threshold: Optional[int] = None  # <0 never, 0 always


@dataclass
class WalConfig:
    # durable write path (core/wal.py group commit; docs/configuration.md
    # "Durability"): 0 = strict — every commit group fsyncs before any
    # caller returns, so an acked write survives a crash; > 0 = bounded-
    # loss cadence in seconds — callers return after the buffered
    # write+flush and a background syncer fsyncs on this interval, the
    # crash loss window. Process-global (WAL files belong to the
    # process, not to one in-process node).
    sync_interval: float = 0.0


@dataclass
class MeshConfig:
    # mesh-local sharded execution (exec/meshgroup.py; docs/
    # configuration.md "Mesh execution"): nodes declaring the same
    # non-empty `group` share an ICI domain — their shards fold into ONE
    # compiled sharded program with in-program collectives instead of
    # per-node HTTP legs. HTTP/DCN remains the transport across groups.
    group: str = ""  # ICI domain id; "" = no mesh-local execution
    min_nodes: int = 2  # group-local owners before the fold engages; 0 disables
    # collective-cost link classes (sched/cost.py transport terms):
    # intra-group reductions ride ICI, cross-group legs ride HTTP/DCN
    ici_gbps: float = 100.0
    dcn_gbps: float = 3.0


@dataclass
class CacheConfig:
    # versioned result cache (core/resultcache.py; docs/configuration.md
    # "Result cache"): Count/TopN/GroupBy results cached keyed on the
    # exact fragment-version vector the plan read — repeats serve from
    # host memory with zero compiled dispatches after a cheap
    # revalidation, and cached Counts are patched in place from the
    # merge barrier's word deltas after set-only staged bursts.
    result_mb: int = 64  # LRU byte budget, MB; 0 disables the cache
    count_repair: bool = True  # incremental Count repair on staged bursts


@dataclass
class CoherenceConfig:
    # cache coherence plane (pilosa_tpu/coherence/; docs/configuration.md
    # "[coherence]"): push invalidation + version leases + query
    # subscriptions. With leases on, a coordinator holding a lease
    # serves fan-out warm hits with ZERO per-query version RTTs —
    # writers push batched version bumps instead; lease expiry degrades
    # safely to the /internal/versions revalidate path, so a dead or
    # partitioned publisher causes staleness bounded by lease-duration,
    # never a wrong answer served as fresh.
    lease_duration: float = 0.0  # lease lifetime, seconds; 0 = leases off
    publish_batch_ms: float = 20.0  # bump batching / flush tick, ms
    max_subscriptions: int = 64  # standing queries per node; 0 = subs off
    sub_poll_interval: float = 5.0  # unleased-shard refresh floor, seconds


@dataclass
class ResizeConfig:
    # live elastic resize (streaming resharding under traffic;
    # docs/configuration.md "Elastic resize"): moving fragments stream as
    # snapshot + live write capture while the old topology keeps serving;
    # writes are never globally frozen
    transfer_concurrency: int = 4  # parallel fragment fetches per node
    cutover_timeout: float = 30.0  # catch-up barrier wall bound, seconds
    resume_policy: str = "resume"  # resume | abort on a failed stream leg


@dataclass
class TierConfig:
    # tiered storage (pilosa_tpu/tier/; docs/configuration.md "Tiered
    # storage"): idle fragments demote to immutable snapshot objects in
    # a shared object store (upload strictly before local delete) and
    # hydrate on demand through the batch admission lane — datasets
    # larger than host RAM + local disk stay queryable, and joining
    # nodes bootstrap from stored snapshots instead of peer-streaming
    # every byte. "" store-path disables the whole plane.
    store_path: str = ""  # shared object-store directory; "" = tier off
    placement: str = "hot"  # default placement: hot | warm | cold
    # per-index placement overrides, "index:placement=cold" entries
    overrides: List[str] = field(default_factory=list)
    demote_after: float = 300.0  # idle seconds before a cold-placement demote
    host_budget_bytes: int = 0  # local snap+wal byte budget; 0 = unlimited
    fetch_concurrency: int = 4  # concurrent store transfers per node


@dataclass
class AntiEntropyConfig:
    interval: float = 0.0  # seconds; 0 disables the loop


@dataclass
class MetricConfig:
    service: str = "expvar"  # none | expvar | prometheus | statsd
    # (reference default: expvar, stats/stats.go:84; statsd pushes
    # DogStatsD datagrams to `host` AND feeds the scrape registry)
    host: str = "localhost:8125"  # statsd daemon address
    poll_interval: float = 30.0


@dataclass
class TracingConfig:
    # query flight recorder (utils/tracing.py; docs/observability.md).
    # `enabled` gates spontaneous ROOT sampling only: an incoming trace
    # header (the sender sampled) and the `profile=true` query option
    # always record, so flight recording works on demand either way.
    enabled: bool = False
    sample_rate: float = 1.0  # fraction of root queries traced
    ring: int = 1024  # spans kept in the per-node ring (/debug/traces)


@dataclass
class TelemetryConfig:
    # cluster telemetry plane (server/telemetry.py;
    # docs/observability.md "Cluster telemetry"): the always-on
    # utilization timeline sampler behind /debug/timeline — each tick
    # also refreshes the devcache/HBM gauges so statsd backends see
    # them without an HTTP scrape
    sample_interval: float = 5.0  # seconds between samples; 0 disables
    ring: int = 720  # utilization samples kept per node (~1h at 5s)


@dataclass
class TLSConfig:
    # Serve the whole HTTP plane (client API + internode) over TLS when
    # certificate+key are set (reference: server/config.go:151-157 TLS
    # block, applied in server.go:222-295). skip_verify disables peer cert
    # verification in the internode client (self-signed deployments);
    # ca_certificate pins a CA instead — the verified alternative.
    certificate: str = ""
    key: str = ""
    skip_verify: bool = False
    ca_certificate: str = ""


@dataclass
class Config:
    data_dir: str = "~/.pilosa-tpu"
    bind: str = "localhost:10101"
    node_id: str = ""  # default: derived from bind
    log_path: str = ""  # empty = stderr
    verbose: bool = False
    long_query_time: float = 0.0  # seconds; 0 disables slow-query logging
    max_writes_per_request: int = 5000
    # bulk-import replica fan-out: shard batches ship to their owner
    # nodes on a bounded thread pool this wide (docs/configuration.md
    # "Ingest")
    import_concurrency: int = 8
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    sched: SchedConfig = field(default_factory=SchedConfig)
    tenants: TenantsConfig = field(default_factory=TenantsConfig)
    hbm: HbmConfig = field(default_factory=HbmConfig)
    bsi: BsiConfig = field(default_factory=BsiConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    wal: WalConfig = field(default_factory=WalConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    coherence: CoherenceConfig = field(default_factory=CoherenceConfig)
    resize: ResizeConfig = field(default_factory=ResizeConfig)
    tier: TierConfig = field(default_factory=TierConfig)
    anti_entropy: AntiEntropyConfig = field(default_factory=AntiEntropyConfig)
    metric: MetricConfig = field(default_factory=MetricConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)

    # -- sources -----------------------------------------------------------

    @classmethod
    def load(
        cls,
        path: Optional[str] = None,
        env: Optional[dict] = None,
        overrides: Optional[dict] = None,
    ) -> "Config":
        """defaults <- TOML file <- PILOSA_TPU_* env <- explicit overrides."""
        cfg = cls()
        if path:
            with open(path, "rb") as f:
                cfg._apply_dict(tomllib.load(f))
        cfg._apply_env(env if env is not None else os.environ)
        if overrides:
            cfg._apply_dict(overrides)
        return cfg

    def _apply_dict(self, d: dict) -> None:
        for k, v in d.items():
            k = k.replace("-", "_")
            if not hasattr(self, k):
                continue
            cur = getattr(self, k)
            if dataclasses.is_dataclass(cur) and isinstance(v, dict):
                for k2, v2 in v.items():
                    k2 = k2.replace("-", "_")
                    if hasattr(cur, k2):
                        setattr(cur, k2, _coerce(getattr(cur, k2), v2))
            else:
                setattr(self, k, _coerce(cur, v))

    def _apply_env(self, env: dict) -> None:
        for name, raw in env.items():
            if not name.startswith(ENV_PREFIX):
                continue
            parts = name[len(ENV_PREFIX):].lower().split("__")
            try:
                if len(parts) == 1:
                    cur = getattr(self, parts[0])
                    setattr(self, parts[0], _coerce(cur, raw))
                elif len(parts) == 2:
                    sect = getattr(self, parts[0])
                    cur = getattr(sect, parts[1])
                    setattr(sect, parts[1], _coerce(cur, raw))
            except AttributeError:
                continue

    # -- dump --------------------------------------------------------------

    def to_toml(self) -> str:
        out = []
        flat = {
            "data-dir": self.data_dir,
            "bind": self.bind,
            "node-id": self.node_id,
            "log-path": self.log_path,
            "verbose": self.verbose,
            "long-query-time": self.long_query_time,
            "max-writes-per-request": self.max_writes_per_request,
            "import-concurrency": self.import_concurrency,
        }
        for k, v in flat.items():
            out.append(f"{k} = {_toml_value(v)}")
        for sect_name, sect in (
            ("cluster", self.cluster),
            ("sched", self.sched),
            ("tenants", self.tenants),
            ("hbm", self.hbm),
            ("bsi", self.bsi),
            ("ingest", self.ingest),
            ("wal", self.wal),
            ("mesh", self.mesh),
            ("cache", self.cache),
            ("coherence", self.coherence),
            ("resize", self.resize),
            ("tier", self.tier),
            ("anti-entropy", self.anti_entropy),
            ("metric", self.metric),
            ("tracing", self.tracing),
            ("telemetry", self.telemetry),
            ("tls", self.tls),
        ):
            out.append(f"\n[{sect_name}]")
            for f_ in dataclasses.fields(sect):
                val = getattr(sect, f_.name)
                if val is None:
                    # TOML has no null: an unset knob (e.g. the AUTO
                    # merge-device-threshold) is expressed by omission
                    continue
                out.append(
                    f"{f_.name.replace('_', '-')} = {_toml_value(val)}"
                )
        return "\n".join(out) + "\n"


def _coerce(current, value):
    if isinstance(current, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(current, int) and not isinstance(current, bool):
        return int(value)
    if isinstance(current, float):
        return float(value)
    if isinstance(current, list):
        if isinstance(value, str):
            return [x.strip() for x in value.split(",") if x.strip()]
        return list(value)
    return value


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return f'"{v}"'


def parse_hosts(hosts: List[str], default_scheme: str = "http"):
    """'node_id@http://host:port' entries -> [(id, uri)]. Bare host:port
    entries get default_scheme — a TLS cluster must seed https:// peer
    URIs or every internode request would send plaintext to a TLS socket."""
    out = []
    for h in hosts:
        if "@" in h:
            nid, uri = h.split("@", 1)
            if not uri.startswith("http"):
                uri = f"{default_scheme}://{uri}"
        else:
            uri = h if h.startswith("http") else f"{default_scheme}://{h}"
            nid = uri.split("//", 1)[-1].replace(":", "-")
        out.append((nid, uri))
    return out
