"""PQL parser — a hand-rolled recursive-descent/backtracking implementation of
the reference grammar /root/reference/pql/pql.peg (83 lines; the whole
language). The generated Go packrat parser (pql/pql.peg.go) is replaced by
direct descent with save/restore backtracking; semantics (arg assembly,
conditionals, duplicate-arg detection) mirror pql/ast.go's builder actions.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

from pilosa_tpu.pql.ast import BETWEEN, Call, Condition, Query

_TIMESTAMP_RE = re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d")
_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_BARE_STR_RE = re.compile(r"[A-Za-z0-9:_-]+")
_NUM_RE = re.compile(r"-?(\d+(\.\d*)?|\.\d+)")
_UINT_RE = re.compile(r"[1-9]\d*|0")
_COND_INT_RE = re.compile(r"-?[1-9]\d*|0")

RESERVED_FIELDS = ("_row", "_col", "_start", "_end", "_timestamp", "_field")

DUPLICATE_ARG_MSG = "duplicate argument provided"  # mirrors ast.go message


class ParseError(Exception):
    def __init__(self, msg: str, pos: int = 0, src: str = ""):
        self.pos = pos
        if src:
            line = src.count("\n", 0, pos) + 1
            col = pos - (src.rfind("\n", 0, pos) + 1) + 1
            msg = f"{msg} at line {line}, col {col}"
        super().__init__(msg)


class _Backtrack(Exception):
    """Internal: alternative failed; try the next one."""


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0
        self.n = len(src)

    # -- low-level ---------------------------------------------------------

    def fail(self, msg: str = "syntax error"):
        raise _Backtrack(msg)

    def sp(self):
        while self.pos < self.n and self.src[self.pos] in " \t\n":
            self.pos += 1

    def lit(self, s: str) -> None:
        if not self.src.startswith(s, self.pos):
            self.fail(f"expected {s!r}")
        self.pos += len(s)

    def try_lit(self, s: str) -> bool:
        if self.src.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def regex(self, rx: re.Pattern) -> str:
        m = rx.match(self.src, self.pos)
        if not m:
            self.fail(f"expected {rx.pattern}")
        self.pos = m.end()
        return m.group()

    def open_paren(self):
        self.lit("(")
        self.sp()

    def close_paren(self):
        self.lit(")")
        self.sp()

    def comma(self):
        self.sp()
        self.lit(",")
        self.sp()

    def try_comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.try_lit(","):
            self.sp()
            return True
        self.pos = save
        return False

    def alt(self, *alternatives):
        """PEG ordered choice with backtracking."""
        for f in alternatives:
            save = self.pos
            try:
                return f()
            except _Backtrack:
                self.pos = save
        self.fail("no alternative matched")

    # -- grammar: Calls ----------------------------------------------------

    def parse_query(self) -> Query:
        q = Query()
        self.sp()
        while self.pos < self.n:
            q.calls.append(self.parse_call())
            self.sp()
        return q

    def parse_call(self) -> Call:
        for name, fn in (
            ("Set", self._special_set),
            ("SetRowAttrs", self._special_set_row_attrs),
            ("SetColumnAttrs", self._special_set_column_attrs),
            ("Clear", self._special_clear),
            ("ClearRow", self._special_clear_row),
            ("Store", self._special_store),
            ("TopN", self._special_posfield_call),
            ("Rows", self._special_posfield_call),
            ("Range", self._special_range),
        ):
            if self.src.startswith(name, self.pos):
                save = self.pos
                try:
                    return fn(name)
                except _Backtrack:
                    self.pos = save
        return self._generic_call()

    # Special forms. Note the PEG is ordered choice: 'Set' matches before
    # 'SetRowAttrs' never happens because peg tries alternatives in order and
    # 'Set' + open fails for 'SetRowAttrs(' (open expects '('); order here
    # tries the longest names first via exact startswith + backtracking.

    def _special_set(self, name: str) -> Call:
        # 'Set' open col comma args (comma timestamp)? close
        if self.src.startswith("SetRowAttrs", self.pos) or self.src.startswith(
            "SetColumnAttrs", self.pos
        ):
            self.fail("not plain Set")
        call = Call(name)
        self.lit("Set")
        self.open_paren()
        self._col(call)
        self.comma()
        self._args(call)
        save = self.pos
        try:
            self.comma()
            ts = self._timestampfmt()
            self._set_arg(call, "_timestamp", ts)
        except _Backtrack:
            self.pos = save
        self.close_paren()
        return call

    def _special_set_row_attrs(self, name: str) -> Call:
        # 'SetRowAttrs' open posfield comma row comma args close
        call = Call(name)
        self.lit("SetRowAttrs")
        self.open_paren()
        self._posfield(call)
        self.comma()
        self._row(call)
        self.comma()
        self._args(call)
        self.close_paren()
        return call

    def _special_set_column_attrs(self, name: str) -> Call:
        call = Call(name)
        self.lit("SetColumnAttrs")
        self.open_paren()
        self._col(call)
        self.comma()
        self._args(call)
        self.close_paren()
        return call

    def _special_clear(self, name: str) -> Call:
        if self.src.startswith("ClearRow", self.pos):
            self.fail("not plain Clear")
        call = Call(name)
        self.lit("Clear")
        self.open_paren()
        self._col(call)
        self.comma()
        self._args(call)
        self.close_paren()
        return call

    def _special_clear_row(self, name: str) -> Call:
        call = Call(name)
        self.lit("ClearRow")
        self.open_paren()
        self._arg(call)
        self.sp()
        self.close_paren()
        return call

    def _special_store(self, name: str) -> Call:
        call = Call(name)
        self.lit("Store")
        self.open_paren()
        call.children.append(self.parse_call())
        self.comma()
        self._arg(call)
        self.sp()
        self.close_paren()
        return call

    def _special_posfield_call(self, name: str) -> Call:
        # 'TopN'/'Rows' open posfield (comma allargs)? close
        call = Call(name)
        self.lit(name)
        self.open_paren()
        self._posfield(call)
        if self.try_comma():
            self._allargs(call)
        self.close_paren()
        return call

    def _special_range(self, name: str) -> Call:
        # 'Range' open field '=' value comma 'from='? ts comma 'to='? ts close
        call = Call(name)
        self.lit("Range")
        self.open_paren()
        fld = self.regex(_FIELD_RE)
        self.sp()
        self.lit("=")
        self.sp()
        self._set_arg(call, fld, self._value(call))
        self.comma()
        self.try_lit("from=")
        self._set_arg(call, "from", self._timestampfmt())
        self.comma()
        self.try_lit("to=")
        self.sp()
        self._set_arg(call, "to", self._timestampfmt())
        self.close_paren()
        return call

    def _generic_call(self) -> Call:
        name = self.regex(_IDENT_RE)
        call = Call(name)
        self.sp()
        self.open_paren()
        self._allargs(call)
        self.try_comma()
        self.close_paren()
        return call

    # -- grammar: args -----------------------------------------------------

    def _allargs(self, call: Call):
        # allargs <- Call (comma Call)* (comma args)? / args / sp
        # Alternatives mutate `call`; on backtrack the partial args/children
        # must be rolled back along with the position.
        def protected(f):
            def g():
                saved_args = dict(call.args)
                saved_children = list(call.children)
                try:
                    return f()
                except _Backtrack:
                    call.args.clear()
                    call.args.update(saved_args)
                    call.children[:] = saved_children
                    raise

            return g

        def calls_then_args():
            call.children.append(self.parse_call())
            while True:
                save = self.pos
                try:
                    self.comma()
                    call.children.append(self.parse_call())
                except _Backtrack:
                    self.pos = save
                    break
            save = self.pos
            try:
                self.comma()
                self._args(call)
            except _Backtrack:
                self.pos = save

        def just_args():
            self._args(call)

        def just_sp():
            self.sp()

        self.alt(protected(calls_then_args), protected(just_args), just_sp)

    def _args(self, call: Call):
        # args <- arg (comma args)? sp
        self._arg(call)
        save = self.pos
        try:
            self.comma()
            self._args(call)
        except _Backtrack:
            self.pos = save
        self.sp()

    def _arg(self, call: Call):
        # arg <- field '=' value / field COND value / conditional
        def eq_form():
            fld = self._field_name()
            self.sp()
            if not self.try_lit("="):
                self.fail("expected =")
            # '==' is a COND, not assignment
            if self.src.startswith("=", self.pos):
                self.fail("actually COND ==")
            self.sp()
            self._set_arg(call, fld, self._value(call))

        def cond_form():
            fld = self._field_name()
            self.sp()
            op = self._cond_op()
            self.sp()
            v = self._value(call)
            self._set_arg(call, fld, Condition(op, v))

        def conditional_form():
            self._conditional(call)

        self.alt(eq_form, cond_form, conditional_form)

    def _cond_op(self) -> str:
        for lit, op in (
            ("><", "><"),
            ("<=", "<="),
            (">=", ">="),
            ("==", "=="),
            ("!=", "!="),
            ("<", "<"),
            (">", ">"),
        ):
            if self.try_lit(lit):
                return op
        self.fail("expected condition operator")

    def _int64(self, v: str) -> int:
        """Parse an integer literal, rejecting values outside int64 (the
        reference's grammar does, pqlpeg_test.go ArgOutOfBounds)."""
        n = int(v)
        if not -(1 << 63) <= n < (1 << 63):
            raise ParseError(
                f"integer literal out of int64 range: {v}", self.pos, self.src
            )
        return n

    def _conditional(self, call: Call):
        # conditional <- condint condLT condfield condLT condint
        # e.g. `5 < f <= 10`
        low = self._int64(self.regex(_COND_INT_RE))
        self.sp()
        op1 = (
            "<=" if self.try_lit("<=")
            else ("<" if self.try_lit("<") else self.fail("expected <"))
        )
        self.sp()
        fld = self.regex(_FIELD_RE)
        self.sp()
        op2 = (
            "<=" if self.try_lit("<=")
            else ("<" if self.try_lit("<") else self.fail("expected <"))
        )
        self.sp()
        high = self._int64(self.regex(_COND_INT_RE))
        self.sp()
        # reference semantics (ast.go:82 endConditional): strict bounds are
        # shifted inward to produce an inclusive BETWEEN.
        if op1 == "<":
            low += 1
        if op2 == "<":
            high -= 1
        self._set_arg(call, fld, Condition(BETWEEN, [low, high]))

    def _field_name(self) -> str:
        for r in RESERVED_FIELDS:
            if self.src.startswith(r, self.pos):
                self.pos += len(r)
                return r
        return self.regex(_FIELD_RE)

    def _posfield(self, call: Call):
        self._set_arg(call, "_field", self.regex(_FIELD_RE))

    def _col(self, call: Call):
        self._pos_value(call, "_col")

    def _row(self, call: Call):
        self._pos_value(call, "_row")

    def _pos_value(self, call: Call, key: str):
        if self.try_lit("'"):
            s = self._quoted_string("'")
            self._set_arg(call, key, s)
        elif self.try_lit('"'):
            s = self._quoted_string('"')
            self._set_arg(call, key, s)
        else:
            self._set_arg(call, key, int(self.regex(_UINT_RE)))

    # -- grammar: values ---------------------------------------------------

    def _value(self, call: Call) -> Any:
        # value <- item / '[' list ']'
        self.sp()
        if self.try_lit("["):
            self.sp()
            items = [self._item(call)]
            while self.try_comma():
                items.append(self._item(call))
            self.sp()
            self.lit("]")
            self.sp()
            return items
        return self._item(call)

    def _item(self, call: Call) -> Any:
        # Ordered per pql.peg:43-53.
        s = self.src
        p = self.pos

        def keyword(word, pyval):
            def f():
                self.lit(word)
                # &(comma / sp close) lookahead
                save = self.pos
                self.sp()
                if self.pos < self.n and self.src[self.pos] in ",)]":
                    self.pos = save
                    return pyval
                self.fail("not a keyword")

            return f

        def timestamp():
            return self._timestampfmt()

        def number():
            v = self.regex(_NUM_RE)
            # must not be followed by ident chars (e.g. `123abc` is a bare string)
            if self.pos < self.n and (self.src[self.pos].isalnum() or self.src[self.pos] in ":_-"):
                self.fail("not a number")
            if "." in v:
                return float(v)
            # int args are int64 on the wire (pqlpeg ArgOutOfBounds)
            return self._int64(v)

        def nested_call():
            name = self.regex(_IDENT_RE)
            self.sp()
            self.open_paren()
            sub = Call(name)
            self._allargs(sub)
            self.try_comma()
            self.close_paren()
            return sub

        def bare_string():
            return self.regex(_BARE_STR_RE)

        def dquoted():
            self.lit('"')
            return self._quoted_string('"')

        def squoted():
            self.lit("'")
            return self._quoted_string("'")

        return self.alt(
            keyword("null", None),
            keyword("true", True),
            keyword("false", False),
            timestamp,
            number,
            nested_call,
            bare_string,
            dquoted,
            squoted,
        )

    def _timestampfmt(self) -> str:
        if self.try_lit('"'):
            ts = self.regex(_TIMESTAMP_RE)
            self.lit('"')
            return ts
        if self.try_lit("'"):
            ts = self.regex(_TIMESTAMP_RE)
            self.lit("'")
            return ts
        return self.regex(_TIMESTAMP_RE)

    def _quoted_string(self, quote: str) -> str:
        out = []
        while self.pos < self.n:
            ch = self.src[self.pos]
            if ch == "\\" and self.pos + 1 < self.n and self.src[self.pos + 1] in (quote, "\\"):
                out.append(self.src[self.pos + 1])
                self.pos += 2
                continue
            if ch == quote:
                self.pos += 1
                return "".join(out)
            out.append(ch)
            self.pos += 1
        self.fail("unterminated string")

    # -- arg assembly ------------------------------------------------------

    def _set_arg(self, call: Call, key: str, value: Any):
        if key in call.args:
            raise ParseError(f"{DUPLICATE_ARG_MSG}: {key}", self.pos, self.src)
        call.args[key] = value


def parse(src: str) -> Query:
    """Parse a PQL string into a Query (reference: pql.ParseString)."""
    p = _Parser(src)
    try:
        return p.parse_query()
    except _Backtrack as e:
        raise ParseError(str(e) or "syntax error", p.pos, src) from None
    except RecursionError:
        raise ParseError("query too deeply nested", p.pos, src) from None
