from pilosa_tpu.pql.ast import Call, Condition, Query  # noqa: F401
from pilosa_tpu.pql.parser import ParseError, parse  # noqa: F401
