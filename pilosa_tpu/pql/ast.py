"""PQL abstract syntax tree.

Reference: /root/reference/pql/ast.go — Query{Calls}, Call{Name, Args,
Children}, Condition{Op, Value} (ast.go:27,263,482). Arg values are Python
ints/floats/bools/None/strings, nested Calls, lists, or Condition objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# Condition ops (reference: pql/token.go GT/LT/GTE/LTE/EQ/NEQ/BETWEEN).
GT = ">"
LT = "<"
GTE = ">="
LTE = "<="
EQ = "=="
NEQ = "!="
BETWEEN = "><"

# Args keys reserved by the grammar (pql.peg:60).
RESERVED = {"_row", "_col", "_start", "_end", "_timestamp", "_field"}


@dataclass
class Condition:
    op: str
    value: Any  # scalar, or [low, high] for BETWEEN

    def __repr__(self) -> str:
        return f"Condition({self.op!r}, {self.value!r})"

    def int_pair(self):
        if not isinstance(self.value, list) or len(self.value) != 2:
            raise ValueError(f"expected two-value condition, got {self.value!r}")
        return int(self.value[0]), int(self.value[1])


@dataclass
class Call:
    name: str
    args: Dict[str, Any] = field(default_factory=dict)
    children: List["Call"] = field(default_factory=list)

    # -- accessors (reference: ast.go:315-392) -----------------------------

    def arg(self, key: str, default=None):
        return self.args.get(key, default)

    def uint_arg(self, key: str) -> Optional[int]:
        v = self.args.get(key)
        if v is None:
            return None
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"argument {key!r} must be an unsigned integer, got {v!r}")
        if v < 0:
            raise ValueError(f"argument {key!r} must be >= 0, got {v}")
        return v

    def int_arg(self, key: str) -> Optional[int]:
        v = self.args.get(key)
        if v is None:
            return None
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"argument {key!r} must be an integer, got {v!r}")
        return v

    def bool_arg(self, key: str) -> Optional[bool]:
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, bool):
            raise ValueError(f"argument {key!r} must be a bool, got {v!r}")
        return v

    def string_arg(self, key: str) -> Optional[str]:
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, str):
            raise ValueError(f"argument {key!r} must be a string, got {v!r}")
        return v

    def call_arg(self, key: str) -> Optional["Call"]:
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, Call):
            raise ValueError(f"argument {key!r} must be a call, got {v!r}")
        return v

    def field_arg(self) -> str:
        """The positional field name (grammar posfield -> args['_field'])."""
        v = self.args.get("_field")
        if not isinstance(v, str):
            raise ValueError(f"{self.name} requires a field argument")
        return v

    def has_conditions(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def condition_args(self):
        return {k: v for k, v in self.args.items() if isinstance(v, Condition)}

    # -- serialization ------------------------------------------------------

    def __str__(self) -> str:
        parts: List[str] = [str(c) for c in self.children]
        for k in sorted(self.args, key=lambda k: (k not in RESERVED, k)):
            v = self.args[k]
            if isinstance(v, Condition):
                parts.append(f"{k} {v.op} {_fmt(v.value)}")
            else:
                parts.append(f"{k}={_fmt(v)}")
        return f"{self.name}({', '.join(parts)})"

    def __repr__(self) -> str:
        return f"Call({self!s})"


def _fmt(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, Call):
        return str(v)
    return str(v)


WRITE_CALLS = {"Set", "Clear", "SetRowAttrs", "SetColumnAttrs"}


@dataclass
class Query:
    calls: List[Call] = field(default_factory=list)

    def write_call_n(self) -> int:
        """Number of mutating calls (reference: ast.go WriteCallN)."""
        return sum(1 for c in self.calls if c.name in WRITE_CALLS)

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)
