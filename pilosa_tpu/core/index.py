"""Index: per-index namespace of fields + existence tracking.

Reference: /root/reference/index.go — fields map (index.go:37), `_exists`
existence field for Not()/existence queries (holder.go:46, index.go:215),
AvailableShards union over fields (index.go:292)."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Set

import numpy as np

from pilosa_tpu.utils.locks import TrackedRLock
from pilosa_tpu.core.field import (
    FIELD_TYPE_SET,
    Field,
    FieldOptions,
    validate_name,
)

EXISTENCE_FIELD_NAME = "_exists"  # reference: existenceFieldName, holder.go:46


class Index:
    def __init__(
        self,
        path: Optional[str],
        name: str,
        *,
        keys: bool = False,
        track_existence: bool = True,
    ):
        validate_name(name)
        self.path = path
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        self._mu = TrackedRLock("index.mu")
        self._fields: Dict[str, Field] = {}
        # result-cache key scope (core/resultcache.py): a process-unique
        # token per Index INSTANCE, so in-process peers holding a
        # same-named index — or a deleted-and-recreated one — can never
        # serve each other's cached results (fragment version counters
        # are per-instance and would collide under a name-based key)
        from pilosa_tpu.core.devcache import new_owner_token

        self._cache_scope = new_owner_token()
        # per-column attributes (reference: index.go columnAttrStore)
        from pilosa_tpu.core.attrs import AttrStore

        self.column_attr_store = AttrStore(
            None if path is None else os.path.join(path, ".col_attrs.json")
        )
        # column key translation (reference: index.go per-index translateStore)
        from pilosa_tpu.core.translate import TranslateStore

        self.translate_store = TranslateStore(
            None if path is None else os.path.join(path, ".keys.translate")
        )

    # ------------------------------------------------------------------

    @property
    def meta_path(self) -> Optional[str]:
        return None if self.path is None else os.path.join(self.path, ".meta.json")

    def open(self) -> "Index":
        if self.path is not None:
            os.makedirs(self.path, exist_ok=True)
            if os.path.exists(self.meta_path):
                with open(self.meta_path) as f:
                    data = json.load(f)
                self.keys = data.get("keys", self.keys)
                self.track_existence = data.get("track_existence", self.track_existence)
            else:
                self.save_meta()
            for fn in sorted(os.listdir(self.path)):
                fdir = os.path.join(self.path, fn)
                if os.path.isdir(fdir) and os.path.exists(
                    os.path.join(fdir, ".meta.json")
                ):
                    f = Field(fdir, self.name, fn, FieldOptions()).open()
                    self._fields[fn] = f
        if self.track_existence and EXISTENCE_FIELD_NAME not in self._fields:
            self._create_existence_field()
        if self.keys:
            self.translate_store.open()
        return self

    def close(self) -> None:
        with self._mu:
            for f in self._fields.values():
                f.close()
            self.translate_store.close()
            self.column_attr_store.close()

    def save_meta(self) -> None:
        if self.path is None:
            return
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"keys": self.keys, "track_existence": self.track_existence}, f
            )
        os.replace(tmp, self.meta_path)

    # ------------------------------------------------------------------

    def _field_path(self, name: str) -> Optional[str]:
        return None if self.path is None else os.path.join(self.path, name)

    def _create_existence_field(self) -> Field:
        f = Field(
            self._field_path(EXISTENCE_FIELD_NAME),
            self.name,
            EXISTENCE_FIELD_NAME,
            FieldOptions(type=FIELD_TYPE_SET, cache_type="none", cache_size=0),
        )
        f.open()
        self._fields[EXISTENCE_FIELD_NAME] = f
        return f

    def create_field(self, name: str, options: Optional[FieldOptions] = None) -> Field:
        with self._mu:
            validate_name(name)
            if name in self._fields:
                raise ValueError(f"field already exists: {name}")
            f = Field(self._field_path(name), self.name, name, options or FieldOptions())
            f.open()
            self._fields[name] = f
            return f

    def create_field_if_not_exists(
        self, name: str, options: Optional[FieldOptions] = None
    ) -> Field:
        with self._mu:
            if name in self._fields:
                return self._fields[name]
            return self.create_field(name, options)

    def field(self, name: str) -> Optional[Field]:
        return self._fields.get(name)

    def fields(self, include_hidden: bool = False) -> List[Field]:
        with self._mu:
            return [
                f
                for n, f in sorted(self._fields.items())
                if include_hidden or not n.startswith("_")
            ]

    def delete_field(self, name: str) -> None:
        with self._mu:
            f = self._fields.pop(name, None)
            if f is None:
                raise KeyError(f"field not found: {name}")
            f.close()
            if f.path is not None:
                import shutil

                shutil.rmtree(f.path, ignore_errors=True)

    def existence_field(self) -> Optional[Field]:
        return self._fields.get(EXISTENCE_FIELD_NAME) if self.track_existence else None

    def track_columns(self, cols: np.ndarray) -> None:
        """Mark columns as existing (row 0 of `_exists`; index.go:215)."""
        ef = self.existence_field()
        if ef is not None and len(cols):
            ef.import_bits(np.zeros(len(cols), np.uint64), cols)

    def available_shards(self) -> Set[int]:
        with self._mu:
            shards: Set[int] = set()
            for f in self._fields.values():
                shards.update(f.available_shards())
            return shards
