"""Time quantum views — port of /root/reference/time.go semantics.

A time field materializes one view per time unit present in its quantum
("YMDH" subsets): `<name>_2019`, `<name>_201907`, `<name>_20190704`,
`<name>_2019070415`. Range queries compute the minimal covering set of views
by walking up from small units to large and back down (time.go:104
viewsByTimeRange).
"""

from __future__ import annotations

from datetime import datetime, timedelta
from typing import List

TIME_FORMAT = "%Y-%m-%dT%H:%M"  # reference TimeFormat "2006-01-02T15:04"

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}


def validate_quantum(q: str) -> None:
    if q not in VALID_QUANTUMS:
        raise ValueError(f"invalid time quantum {q!r}")


def parse_time(t) -> datetime:
    """Accepts the reference's formats: '2006-01-02T15:04' string or unix
    seconds int (time.go:220 parseTime)."""
    if isinstance(t, str):
        return datetime.strptime(t, TIME_FORMAT)
    if isinstance(t, (int, float)):
        return datetime.utcfromtimestamp(int(t))
    if isinstance(t, datetime):
        return t
    raise ValueError("arg must be a timestamp")


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    if unit == "Y":
        return f"{name}_{t.strftime('%Y')}"
    if unit == "M":
        return f"{name}_{t.strftime('%Y%m')}"
    if unit == "D":
        return f"{name}_{t.strftime('%Y%m%d')}"
    if unit == "H":
        return f"{name}_{t.strftime('%Y%m%d%H')}"
    return ""


def views_by_time(name: str, t: datetime, quantum: str) -> List[str]:
    """All unit views a timestamped bit lands in (time.go:92 viewsByTime)."""
    return [v for unit in quantum if (v := view_by_time_unit(name, t, unit))]


def _add_month(t: datetime) -> datetime:
    # time.go:181 addMonth: clamp to day 1 for late-month days to avoid
    # Jan 31 + 1mo = Mar 2.
    if t.day > 28:
        t = t.replace(day=1)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _add_year(t: datetime) -> datetime:
    try:
        return t.replace(year=t.year + 1)
    except ValueError:  # Feb 29 + 1y normalizes to Mar 1 (Go AddDate)
        return t.replace(year=t.year + 1, month=3, day=1)


def _next_year_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_year(t)
    return nxt.year == end.year or end > nxt


def _go_add_months(t: datetime, n: int) -> datetime:
    """Go time.AddDate(0,n,0) semantics: day overflow normalizes forward
    (Jan 31 + 1mo = Mar 2/3)."""
    y = t.year + (t.month - 1 + n) // 12
    m = (t.month - 1 + n) % 12 + 1
    return datetime(y, m, 1, t.hour, t.minute) + timedelta(days=t.day - 1)


def _next_month_gte(t: datetime, end: datetime) -> bool:
    nxt = _go_add_months(t, 1)
    if (nxt.year, nxt.month) == (end.year, end.month):
        return True
    return end > nxt


def _next_day_gte(t: datetime, end: datetime) -> bool:
    nxt = t + timedelta(days=1)
    if (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day):
        return True
    return end > nxt


def min_max_view_times(view_names, quantum: str):
    """Time span covered by existing time views: (min_start, max_end_exclusive),
    or (None, None) when there are no time views (reference: time.go:237
    minMaxViews + timeOfView)."""
    suffixes = []
    for vname in view_names:
        suffix = vname.rsplit("_", 1)[-1]
        if suffix.isdigit() and len(suffix) in (4, 6, 8, 10):
            suffixes.append(suffix)
    if not suffixes:
        return None, None
    lo, hi = min(suffixes), max(suffixes)
    fmts = {4: "%Y", 6: "%Y%m", 8: "%Y%m%d", 10: "%Y%m%d%H"}

    def start_of(s: str) -> datetime:
        return datetime.strptime(s, fmts[len(s)])

    def end_of(s: str) -> datetime:
        t = start_of(s)
        if len(s) == 4:
            return _add_year(t)
        if len(s) == 6:
            return _go_add_months(t, 1)
        if len(s) == 8:
            return t + timedelta(days=1)
        return t + timedelta(hours=1)

    return start_of(lo), end_of(hi)


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> List[str]:
    """Minimal covering view set for [start, end) (time.go:104)."""
    has_y = "Y" in quantum
    has_m = "M" in quantum
    has_d = "D" in quantum
    has_h = "H" in quantum

    t = start
    results: List[str] = []

    # Walk up from smallest units to largest.
    if has_h or has_d or has_m:
        while t < end:
            if has_h:
                if not _next_day_gte(t, end):
                    break
                elif t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = t + timedelta(hours=1)
                    continue
            if has_d:
                if not _next_month_gte(t, end):
                    break
                elif t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = t + timedelta(days=1)
                    continue
            if has_m:
                if not _next_year_gte(t, end):
                    break
                elif t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from largest units to smallest.
    while t < end:
        if has_y and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_year(t)
        elif has_m and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_month(t)
        elif has_d and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t = t + timedelta(days=1)
        elif has_h:
            results.append(view_by_time_unit(name, t, "H"))
            t = t + timedelta(hours=1)
        else:
            break

    return results
