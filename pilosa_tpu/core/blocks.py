"""Block checksum primitives for fragment comparison.

Reference: /root/reference/fragment.go:81 (HashBlockSize = 100 rows),
:2814-2838 (blockHasher over the (row,col) pair stream), :1762-1874
(Blocks/checksum invalidation).

Lives in core/ because fragments own their pair data; the cluster layer's
anti-entropy (cluster/antientropy.py) builds its replica-merge protocol on
top of these digests — core stays cluster-unaware."""

from __future__ import annotations

import hashlib
from typing import Dict, Tuple

import numpy as np

HASH_BLOCK_SIZE = 100  # rows per block (fragment.go:81)


def block_id_of(row_id: int) -> int:
    return row_id // HASH_BLOCK_SIZE


def block_checksums(
    rows_cols: Tuple[np.ndarray, np.ndarray]
) -> Dict[int, bytes]:
    """Per-block digest of a fragment's (row, in-shard col) pairs.

    Returns {block_id: 16-byte digest}; blocks with no bits are absent
    (matching the reference, which only reports blocks holding data)."""
    rows, cols = rows_cols
    if len(rows) == 0:
        return {}
    rows = np.asarray(rows, dtype=np.uint64)
    cols = np.asarray(cols, dtype=np.uint64)
    order = np.lexsort((cols, rows))
    rows, cols = rows[order], cols[order]
    block_ids = (rows // HASH_BLOCK_SIZE).astype(np.int64)
    out: Dict[int, bytes] = {}
    # split at block boundaries
    boundaries = np.nonzero(np.diff(block_ids))[0] + 1
    starts = np.concatenate(([0], boundaries))
    ends = np.concatenate((boundaries, [len(rows)]))
    for s, e in zip(starts, ends):
        bid = int(block_ids[s])
        h = hashlib.blake2b(digest_size=16)
        h.update(rows[s:e].tobytes())
        h.update(cols[s:e].tobytes())
        out[bid] = h.digest()
    return out
