"""Roaring bitmap interchange format (pilosa dialect + official read).

The reference persists fragments as roaring files and ships them between
nodes in the same format (reference: docs/architecture.md:11-27; writer
roaring/roaring.go WriteTo at :1046; pilosa iterator :1262; official-format
reader readOfficialHeader at :5315). Our fragments store dense blocks (see
core/wal.py), so roaring here is purely an *interchange* codec: it decodes
any roaring file into sorted uint64 bit positions and encodes positions back
into the pilosa dialect, for:

  - `/internal/.../import-roaring/{shard}` zero-parse bulk ingest
    (reference: api.go:368 ImportRoaring),
  - CLI `inspect` / `check` of reference-produced .bitmap files,
  - export in a format the reference's tooling can read.

Format (pilosa dialect, all little-endian):
  bytes 0-1  magic 12348; byte 2 version (0); byte 3 flags
  bytes 4-7  u32 container count
  descriptive header, 12 B/container: u64 key, u16 type, u16 cardinality-1
  offset header, 4 B/container: u32 absolute file offset of container data
  container data: array = u16[n]; bitmap = u64[1024];
                  run = u16 run count, then (u16 start, u16 last) pairs
  anything after the last container is an op log (ignored here; our WAL is
  a sidecar file, core/wal.py).

Official RoaringFormatSpec (read-only): cookie 12346 (no runs; offset table
present) or low16==12347 (count = hi16+1; is-run bitset; containers packed
sequentially, runs stored as (start, length)); u16 keys.

A native C++ implementation of the same codec (pilosa_tpu/native) is used
when available; these numpy paths are the fallback and the differential
oracle for it.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

MAGIC = 12348
OFFICIAL_COOKIE = 12347
OFFICIAL_COOKIE_NORUN = 12346

TYPE_ARRAY = 1
TYPE_BITMAP = 2
TYPE_RUN = 3

ARRAY_MAX_SIZE = 4096  # reference: roaring/roaring.go:1940
HEADER_BASE_SIZE = 8
# Official spec: run-cookie files carry an offset header iff they have at
# least this many containers. (The Go reference ignores it and misparses
# such files — newOfficialRoaringIterator reads sequentially; we honor it.)
NO_OFFSET_THRESHOLD = 4

_U16 = np.dtype("<u2")
_U32 = np.dtype("<u4")
_U64 = np.dtype("<u8")


class RoaringError(ValueError):
    pass


def _expand_runs(starts: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """[s0,s1..], [n0,n1..] -> concatenated aranges, vectorized."""
    lengths = lengths.astype(np.int64)
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, dtype=np.uint32)
    excl = np.concatenate(([0], np.cumsum(lengths)[:-1]))
    base = np.repeat(starts.astype(np.int64) - excl, lengths)
    return (base + np.arange(total, dtype=np.int64)).astype(np.uint32)


def _bitmap_words_to_lows(words: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint32)


def _lows_to_bitmap_words(lows: np.ndarray) -> np.ndarray:
    bits = np.zeros(1 << 16, dtype=np.uint8)
    bits[lows] = 1
    return np.packbits(bits, bitorder="little").view(_U64)


def _runs_of(lows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Sorted u16 lows -> (run starts, run lasts)."""
    if len(lows) == 0:
        return lows, lows
    brk = np.nonzero(np.diff(lows.astype(np.int64)) != 1)[0]
    starts = np.concatenate(([lows[0]], lows[brk + 1]))
    lasts = np.concatenate((lows[brk], [lows[-1]]))
    return starts, lasts


def decode(data: bytes) -> np.ndarray:
    """Any roaring file -> sorted uint64 bit positions (ignores op log)."""
    if len(data) < 8:
        raise RoaringError(f"buffer too small: {len(data)} bytes")
    cookie = struct.unpack_from("<I", data, 0)[0]
    if cookie & 0xFFFF == MAGIC:
        return _decode_pilosa(data)
    if cookie == OFFICIAL_COOKIE_NORUN or cookie & 0xFFFF == OFFICIAL_COOKIE:
        return _decode_official(data)
    raise RoaringError(f"unknown roaring cookie: {cookie & 0xFFFF}")


def _decode_pilosa(data: bytes) -> np.ndarray:
    version = data[2]
    if version != 0:
        raise RoaringError(f"unsupported roaring file version {version}")
    n_keys = struct.unpack_from("<I", data, 4)[0]
    if n_keys == 0:
        return np.empty(0, dtype=np.uint64)
    hdr_end = HEADER_BASE_SIZE + 12 * n_keys
    off_end = hdr_end + 4 * n_keys
    if off_end > len(data):
        raise RoaringError("descriptive/offset header overruns buffer")
    hdr = np.frombuffer(data, dtype=np.uint8, count=12 * n_keys, offset=HEADER_BASE_SIZE)
    keys = hdr.reshape(n_keys, 12)[:, 0:8].copy().view(_U64).reshape(n_keys)
    types = hdr.reshape(n_keys, 12)[:, 8:10].copy().view(_U16).reshape(n_keys)
    cards = hdr.reshape(n_keys, 12)[:, 10:12].copy().view(_U16).reshape(n_keys).astype(np.int64) + 1
    offsets = np.frombuffer(data, dtype=_U32, count=n_keys, offset=hdr_end).astype(np.int64)
    if len(np.unique(keys)) != n_keys or not np.all(np.diff(keys.astype(np.int64)) > 0):
        raise RoaringError("container keys not strictly increasing")
    out: List[np.ndarray] = []
    for i in range(n_keys):
        lows = _decode_container(
            data, int(types[i]), int(offsets[i]), int(cards[i]), runs_as_last=True
        )
        out.append((keys[i] << np.uint64(16)) | lows.astype(np.uint64))
    return np.concatenate(out) if out else np.empty(0, dtype=np.uint64)


def _decode_container(
    data: bytes, ctype: int, offset: int, card: int, runs_as_last: bool
) -> np.ndarray:
    if ctype == TYPE_ARRAY:
        end = offset + 2 * card
        if offset < 0 or end > len(data):
            raise RoaringError("array container overruns buffer")
        return np.frombuffer(data, dtype=_U16, count=card, offset=offset).astype(np.uint32)
    if ctype == TYPE_BITMAP:
        if offset < 0 or offset + 8192 > len(data):
            raise RoaringError("bitmap container overruns buffer")
        words = np.frombuffer(data, dtype=_U64, count=1024, offset=offset)
        return _bitmap_words_to_lows(words)
    if ctype == TYPE_RUN:
        if offset < 0 or offset + 2 > len(data):
            raise RoaringError("run container overruns buffer")
        n_runs = struct.unpack_from("<H", data, offset)[0]
        end = offset + 2 + 4 * n_runs
        if end > len(data):
            raise RoaringError("run container overruns buffer")
        pairs = np.frombuffer(data, dtype=_U16, count=2 * n_runs, offset=offset + 2)
        starts = pairs[0::2].astype(np.int64)
        seconds = pairs[1::2].astype(np.int64)
        lengths = (seconds - starts + 1) if runs_as_last else (seconds + 1)
        if np.any(lengths <= 0) or np.any(starts + lengths - 1 > 0xFFFF):
            raise RoaringError("invalid run bounds")
        return _expand_runs(starts, lengths)
    raise RoaringError(f"unknown container type {ctype}")


def _decode_official(data: bytes) -> np.ndarray:
    cookie = struct.unpack_from("<I", data, 0)[0]
    pos = 4
    if cookie == OFFICIAL_COOKIE_NORUN:
        n_keys = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        run_bitset = None
    else:
        n_keys = (cookie >> 16) + 1
        nbytes = (n_keys + 7) // 8
        run_bitset = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8, count=nbytes, offset=pos), bitorder="little"
        )
        pos += nbytes
    if n_keys == 0:
        return np.empty(0, dtype=np.uint64)
    if n_keys > (1 << 16):
        raise RoaringError("more than 2^16 containers")
    hdr = np.frombuffer(data, dtype=_U16, count=2 * n_keys, offset=pos)
    pos += 4 * n_keys
    keys = hdr[0::2].astype(np.uint64)
    if n_keys > 1 and not np.all(keys[1:] > keys[:-1]):
        # the decode() contract is sorted unique positions; the official
        # format requires strictly increasing container keys
        raise RoaringError("container keys not strictly increasing")
    cards = hdr[1::2].astype(np.int64) + 1
    offsets: Optional[np.ndarray] = None
    if run_bitset is None or n_keys >= NO_OFFSET_THRESHOLD:
        # offset table present: always for the no-run dialect, and for the
        # run dialect at >= NO_OFFSET_THRESHOLD containers (official spec)
        if pos + 4 * n_keys > len(data):
            raise RoaringError("offset table overruns buffer")
        offsets = np.frombuffer(data, dtype=_U32, count=n_keys, offset=pos).astype(np.int64)
        pos += 4 * n_keys
    out: List[np.ndarray] = []
    for i in range(n_keys):
        card = int(cards[i])
        if run_bitset is not None and run_bitset[i]:
            ctype = TYPE_RUN
        elif card <= ARRAY_MAX_SIZE:
            ctype = TYPE_ARRAY
        else:
            ctype = TYPE_BITMAP
        off = int(offsets[i]) if offsets is not None else pos
        lows = _decode_container(data, ctype, off, card, runs_as_last=False)
        if offsets is None:
            if ctype == TYPE_ARRAY:
                pos = off + 2 * card
            elif ctype == TYPE_BITMAP:
                pos = off + 8192
            else:
                n_runs = struct.unpack_from("<H", data, off)[0]
                pos = off + 2 + 4 * n_runs
        out.append((keys[i] << np.uint64(16)) | lows.astype(np.uint64))
    return np.concatenate(out) if out else np.empty(0, dtype=np.uint64)


def encode(positions: np.ndarray) -> bytes:
    """Sorted-or-not uint64 positions -> pilosa-dialect roaring bytes.

    Container encodings are picked by serialized size (the reference's
    optimize(), roaring/roaring.go:2334): run if strictly smallest, else
    array for cardinality <= 4096, else bitmap.
    """
    positions = np.asarray(positions, dtype=np.uint64)
    if len(positions):
        positions = np.unique(positions)
    keys_all = positions >> np.uint64(16)
    lows_all = (positions & np.uint64(0xFFFF)).astype(np.uint32)
    keys, key_starts, counts = np.unique(keys_all, return_index=True, return_counts=True)
    n_keys = len(keys)

    header = bytearray()
    header += struct.pack("<HBB", MAGIC, 0, 0)
    header += struct.pack("<I", n_keys)
    desc = bytearray()
    offs = bytearray()
    payloads: List[bytes] = []
    offset = HEADER_BASE_SIZE + 16 * n_keys
    for i in range(n_keys):
        lows = lows_all[key_starts[i] : key_starts[i] + counts[i]]
        n = len(lows)
        starts, lasts = _runs_of(lows)
        size_run = 2 + 4 * len(starts)
        size_array = 2 * n
        if size_run < min(size_array, 8192):
            ctype = TYPE_RUN
            pairs = np.empty(2 * len(starts), dtype=_U16)
            pairs[0::2] = starts.astype(_U16)
            pairs[1::2] = lasts.astype(_U16)
            payload = struct.pack("<H", len(starts)) + pairs.tobytes()
        elif n <= ARRAY_MAX_SIZE:
            ctype = TYPE_ARRAY
            payload = lows.astype(_U16).tobytes()
        else:
            ctype = TYPE_BITMAP
            payload = _lows_to_bitmap_words(lows).tobytes()
        desc += struct.pack("<QHH", int(keys[i]), ctype, n - 1)
        offs += struct.pack("<I", offset)
        payloads.append(payload)
        offset += len(payload)
    return bytes(header) + bytes(desc) + bytes(offs) + b"".join(payloads)


def inspect(data: bytes) -> dict:
    """Summary of a roaring file (for CLI inspect/check)."""
    cookie = struct.unpack_from("<I", data, 0)[0]
    dialect = (
        "pilosa"
        if cookie & 0xFFFF == MAGIC
        else "official"
        if cookie == OFFICIAL_COOKIE_NORUN or cookie & 0xFFFF == OFFICIAL_COOKIE
        else "unknown"
    )
    positions = decode(data)
    return {
        "dialect": dialect,
        "bit_count": int(len(positions)),
        "container_count": int(struct.unpack_from("<I", data, 4)[0])
        if dialect == "pilosa"
        else None,
        "max_position": int(positions[-1]) if len(positions) else None,
    }
