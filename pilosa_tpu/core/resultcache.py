"""Versioned result cache with incremental count repair.

The canonical Pilosa workload (PAPER.md §L2) is a dashboard fleet
re-issuing the same segmentation queries every few seconds; every layer
below already speaks fragment versions (version-salted extent keys,
version-salted mesh tally bundles, the merge barrier's per-fragment word
deltas). This module lifts that one level: it caches query RESULTS —
Count scalars, TopN tallies, GroupBy matrices — keyed on the canonical
query text plus the exact fragment-version vector the plan read, with
two freshness paths:

- **revalidation**: a repeat query re-collects the current version
  vector (lock-free monotonic reads — every mutation funnel bumps
  `Fragment.version`); an unchanged vector means the stored result is
  bit-identical to what a recompute would produce, so it is served from
  host memory with zero compiled dispatches and zero device reads.
- **incremental repair** (Counts over monotone row trees): the merge
  barrier's `FragMerge.word_delta` is exactly the information needed to
  patch a cached popcount without re-staging any operand. The single
  plain Row case is `count(new) = count(old) + popcount(delta & ~old)`
  for a set-only staged burst, where `old` is the row's host words at
  the burst's base version (captured by the barrier BEFORE the delta
  layer parks, core/merge.py). Pure Intersect/Union trees of plain
  Rows (`repair_spec`) generalize it: per merged shard the patch is
  `popcount(op(new leaf words)) - popcount(op(old leaf words))` over
  the changed word indexes, with same-view leaf words coming from the
  barrier's capture (one consistent snapshot) and other-view leaf
  words read from the live fragments at staged-base
  (`premerge_row_words`) OUTSIDE the cache lock — a deferred patch
  job that re-validates the entry's whole vector before committing
  and drops the entry on any doubt. Clears, mutex writes and version
  gaps make the delta non-monotone; those entries fall back to
  recompute.
- **structural re-key** (TopN/GroupBy, and Counts the patch formula
  cannot cover): entries carry `dep_rows` — per (field, view), the
  exact row set the result depends on, or None for "any row" (a
  TopN's tallied field, a GroupBy's Rows fields). A merge whose burst
  provably touched no dependent row re-keys the entry to the merged
  versions without recompute; anything else drops.

Scoping: one process-global RESULT_CACHE serves every in-process node
(the multi-node test harnesses run several NodeServers in one process).
Keys carry the owning Index's `_cache_scope` token and version-vector
elements carry per-View `_stack_token`s, so two nodes holding
same-named indexes can never serve each other's entries — version
counters are per-fragment-instance and would otherwise collide.

Invalidation rides the existing funnels: `Fragment.on_mutate` (via the
owning View) reports the mutated shard — non-repairable entries
covering it drop eagerly, repairable Count entries stay for the repair
window; `View.sync_pending` reports the barrier's merges — Count
entries patch in place (or re-key when the burst missed their row),
everything else stale-drops. Entries a hook never reaches are still
safe: revalidation makes a stale entry unservable (versions only ever
grow), it just waits for LRU.
"""

from __future__ import annotations

import copy
import weakref
from collections import OrderedDict
from typing import Any, Dict, Hashable, Iterable, List, Optional, Set, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.utils.race import race_checked

# Default LRU byte budget ([cache] result-cache-mb knob; 0 disables the
# cache outright — get/put become no-ops).
DEFAULT_BUDGET_BYTES = 64 << 20

# Keys executed through an RPC-assembled version vector (HTTP fan-out
# coordinators) only start caching on their SECOND sighting: collecting
# remote versions costs a round trip per peer, and paying it for
# one-off queries would tax every cold query to speed up none.
_CANDIDATE_CAP = 1024

_UNSET = object()


def _popcount(words: np.ndarray) -> int:
    """Exact popcount of a uint32 word array (small: delta words only)."""
    if not len(words):
        return 0
    return int(
        np.unpackbits(np.ascontiguousarray(words).view(np.uint8)).sum()
    )


def _op_popcount(op: str, arrays: list) -> int:
    acc = arrays[0]
    fn = np.bitwise_and if op == "and" else np.bitwise_or
    for a in arrays[1:]:
        acc = fn(acc, a)
    return _popcount(acc)


def _tree_delta(op: str, changed, same, other) -> int:
    """popcount(op(new leaves)) - popcount(op(old leaves)) over one
    shard's changed word selection. `changed` holds (old, new) word
    pairs for the merging view's touched leaves; `same` (untouched
    same-view leaves, from the barrier capture) and `other` (other-view
    operands, read at their pinned versions) are identical at both
    evaluations — which is exactly why the difference telescopes to
    the true count delta across sequential per-view merges."""
    fixed = list(same) + list(other)
    old_arrays = [o for o, _ in changed] + fixed
    new_arrays = [n for _, n in changed] + fixed
    return _op_popcount(op, new_arrays) - _op_popcount(op, old_arrays)


def _result_nbytes(kind: str, result: Any) -> int:
    if kind == "count":
        return 32
    # per-element rates sized to the real Python object graphs (a
    # GroupCount carries a FieldRow list; a Pair is a small dataclass):
    # a high-cardinality GroupBy must charge the budget roughly what it
    # costs in RSS, or a 64 MB knob would admit hundreds of real MB
    per = 384 if kind == "groupby" else 112
    try:
        return 64 + per * len(result)
    except TypeError:
        return 256


def _vector_nbytes(vector: tuple) -> int:
    n = 64
    for elem in vector:
        n += 48
        if elem[0] == "v":
            n += 16 * len(elem[5])
    return n


class _Entry:
    """One cached result.

    `vector` is a tuple of elements, one per (node, field, view) the
    query read:

      ("v", node, field, view, ident, shards, versions)
          ident = the View's `_stack_token` (local / in-process mesh
          member) or (boot_id, token) for a remote node's view —
          instance identity, so delete/recreate or a peer restart can
          never alias an old entry back to life;
      ("m", node, field, view)
          the field/view did not exist ("" view = field missing); its
          materialization changes the element shape, forcing a miss.

    `repair_spec` is set only for Counts over pure monotone trees —
    ("and"|"or", ((field, view, row), ...)) for Count(Intersect/Union
    of plain Rows); the single plain Row case is a one-leaf "and".
    The leaves' merged word deltas can patch the cached scalar in
    place (note_merges).

    `dep_rows` maps (field, view) -> frozenset(rows) | None: the exact
    rows the result depends on per referenced view (None / missing =
    depends on every row). A merge whose burst is disjoint from an
    exact dep set re-keys the entry without recompute."""

    __slots__ = (
        "key", "kind", "index", "text", "result", "vector", "repair_spec",
        "dep_rows", "clocks", "maybe_stale", "nbytes",
    )

    def __init__(
        self,
        key: tuple,
        kind: str,
        index: str,
        text: str,
        result: Any,
        vector: tuple,
        repair_spec: Optional[tuple],
        clocks: Optional[tuple] = None,
        dep_rows: Optional[dict] = None,
    ) -> None:
        self.key = key
        self.kind = kind
        self.index = index
        self.text = text
        self.result = result
        self.vector = vector
        self.repair_spec = repair_spec
        self.dep_rows = dep_rows
        # per-view mutation-clock vector (View.mutation_clock) read
        # BEFORE the version vector: clock-equal implies version-equal,
        # so warm repeats revalidate on one integer per view instead of
        # walking the shard axis. None = fall back to the exact vector.
        self.clocks = clocks
        # a covered mutation was observed since the entry last proved
        # fresh (store / hit / in-place repair). Drives the admission
        # cost discount only — a maybe-stale entry must not admit a
        # recompute byte-free (sched/cost.py); serving correctness
        # never reads it.
        self.maybe_stale = False
        extra = 0
        if repair_spec is not None:
            extra += 48 * len(repair_spec[1])
        if dep_rows:
            extra += sum(
                32 + 8 * (len(rows) if rows is not None else 0)
                for rows in dep_rows.values()
            )
        self.nbytes = (
            len(text)
            + _result_nbytes(kind, result)
            + _vector_nbytes(vector)
            + extra
        )

    def spec_rows(self, field: str, view: str) -> frozenset:
        """Leaf rows of `repair_spec` living in (field, view)."""
        if self.repair_spec is None:
            return frozenset()
        return frozenset(
            r for f, v, r in self.repair_spec[1] if f == field and v == view
        )


@race_checked(exclude=(
    # [cache] knobs: written by NodeServer construction/configure, read
    # lock-free on the hot lookup paths — a racy read sees either the
    # old or the new setting, both valid configurations (GIL-atomic
    # int/bool reads; entries themselves stay fully lock-guarded)
    "_budget",
    "_repair_enabled",
))
class ResultCache:
    """LRU byte-budgeted store of versioned query results (one
    process-global instance, RESULT_CACHE, like core/devcache.py)."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES) -> None:
        self._mu = TrackedLock("resultcache.mu")
        self._budget = int(budget_bytes)
        self._repair_enabled = True
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        # view token -> keys whose vector covers it (invalidation/repair)
        self._by_token: Dict[int, Set[tuple]] = {}
        # index name -> resident bytes (per-tenant attribution; feeds the
        # cache.resident_bytes{index} gauge and quota work)
        self._by_index: Dict[str, int] = {}
        # (index, field, view) -> row -> refcount of repairable Count
        # entries interested in that row's pre-merge words (the merge
        # barrier's old-words capture hook, core/merge.py)
        self._interest: Dict[tuple, Dict[int, int]] = {}
        # (scope, text) -> live entry keys (admission cost discount)
        self._by_text: Dict[tuple, Set[tuple]] = {}
        # keys seen once but not yet cached (RPC-vector gating)
        self._candidates: "OrderedDict[tuple, bool]" = OrderedDict()
        # per-index (tenant) byte quotas ([tenants] section; 0 / absent
        # = unlimited): an index is held to its quota even when the
        # global budget has room, and under global pressure over-quota
        # owners evict first — tenant A's microsecond-serve entries
        # survive tenant B's flood
        self._tenant_quota_default = 0
        self._tenant_quota: Dict[str, int] = {}
        self._quota_evictions_index: Dict[str, int] = {}
        # (scope, text) -> pin refcount: subscription-pinned programs.
        # Pins are keyed on the TEXT, not the entry, so a store after a
        # recompute is born pinned; eviction skips pinned entries (a
        # pinned push program evicted under pressure would silently
        # turn every push into a full recompute).
        self._pins: Dict[tuple, int] = {}
        # view token -> weakref(View): the deferred tree-patch jobs read
        # other operands' premerge words OUTSIDE this cache's lock, and
        # resolve the owning View here (registered at View.open, dropped
        # with drop_view).
        self._views: Dict[int, Any] = {}
        self._counters: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "revalidations": 0,
            "repairs": 0,
            "tree_repairs": 0,
            "rekeys": 0,
            "evictions": 0,
            "stores": 0,
            "quota_evictions": 0,
        }

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        budget_bytes: Any = _UNSET,
        repair: Any = _UNSET,
        tenant_default_bytes: Any = _UNSET,
        tenant_overrides: Any = _UNSET,
    ) -> None:
        """Install the server's [cache] knobs (cli/config.py ->
        server/node.py) and the [tenants] per-index cache quotas.
        Process-global like the [hbm] knobs: all in-process nodes share
        one store (entries stay node-scoped via the index/view tokens in
        their keys)."""
        with self._mu:
            if budget_bytes is not _UNSET:
                self._budget = int(budget_bytes)
            if repair is not _UNSET:
                self._repair_enabled = bool(repair)
            if tenant_default_bytes is not _UNSET:
                self._tenant_quota_default = max(0, int(tenant_default_bytes))
            if tenant_overrides is not _UNSET:
                self._tenant_quota = {
                    k: max(0, int(v))
                    for k, v in (tenant_overrides or {}).items()
                }
            self._evict_over_budget_locked()

    @property
    def budget_bytes(self) -> int:
        return self._budget

    @property
    def repair_enabled(self) -> bool:
        return self._repair_enabled

    # -- lookup / store -----------------------------------------------------

    def get(
        self, key: tuple, vector: Optional[tuple], recount: bool = True
    ) -> Tuple[bool, Any]:
        """(found, result). A hit requires the entry's stored vector to
        EQUAL the caller's freshly collected one — identical fragment
        versions mean identical content, so the stored result is what a
        recompute would return. `recount=False` suppresses the miss
        counter (the repair retry re-gets after running the barrier)."""
        if vector is None or self._budget <= 0:
            return False, None
        with self._mu:
            e = self._entries.get(key)
            if e is not None and e.vector == vector:
                self._entries.move_to_end(key)
                self._counters["hits"] += 1
                self._counters["revalidations"] += 1
                e.maybe_stale = False
                result = e.result
                kind = e.kind
            else:
                if recount:
                    self._counters["misses"] += 1
                return False, None
        if kind == "count":
            return True, result
        return True, copy.deepcopy(result)

    def get_by_clock(
        self, key: tuple, clocks: Optional[tuple]
    ) -> Tuple[bool, Any]:
        """(found, result): the O(#views) fast path — serve when the
        caller's freshly read per-view mutation clocks equal the
        entry's. Sound because every fragment-version bump also bumps
        its view's clock (and clocks were read BEFORE the entry's
        vector at store/refresh time): clock-equal ⇒ zero mutation
        events since ⇒ version-vector-equal. Misses are silent — the
        caller falls back to the exact vector path, which counts."""
        if clocks is None or self._budget <= 0:
            return False, None
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.clocks is None or e.clocks != clocks:
                return False, None
            self._entries.move_to_end(key)
            self._counters["hits"] += 1
            self._counters["revalidations"] += 1
            e.maybe_stale = False
            result = e.result
            kind = e.kind
        if kind == "count":
            return True, result
        return True, copy.deepcopy(result)

    def refresh_clocks(self, key: tuple, clocks: Optional[tuple]) -> None:
        """Arm the clock fast path after a successful exact-vector
        revalidation. `clocks` MUST have been read before the vector
        the caller just matched — a write landing in between then keeps
        the fast path disarmed (live clock moved past), never wrong."""
        if clocks is None:
            return
        with self._mu:
            e = self._entries.get(key)
            if e is not None:
                e.clocks = clocks

    def count_miss(self) -> None:
        """Book one lookup that concluded a miss. The executor defers
        this until the repair retry has also failed, so one logical
        lookup never records both a miss and a hit (a repaired serve
        would otherwise read as cacheHitRate 0.5 on a 100%-served
        dashboard)."""
        with self._mu:
            self._counters["misses"] += 1

    def repairable(self, key: tuple) -> bool:
        """Whether a miss on `key` is worth a repair attempt: a live
        entry with a repair spec or exact dep rows (re-keyable), and
        repair enabled. The caller then runs the read barrier (which
        fires note_merges) and re-gets."""
        if not self._repair_enabled:
            return False
        with self._mu:
            e = self._entries.get(key)
            return e is not None and (
                e.repair_spec is not None or e.dep_rows is not None
            )

    def note_candidate(self, key: tuple) -> bool:
        """Record a sighting of an RPC-vector key; True when the key was
        already seen (worth paying the version round trips now)."""
        with self._mu:
            if key in self._entries:
                return True
            if key in self._candidates:
                self._candidates.move_to_end(key)
                return True
            self._candidates[key] = True
            while len(self._candidates) > _CANDIDATE_CAP:
                self._candidates.popitem(last=False)
            return False

    def put(
        self,
        key: tuple,
        kind: str,
        index: str,
        text: str,
        result: Any,
        vector: tuple,
        repair_row: Optional[int] = None,
        clocks: Optional[tuple] = None,
        repair_spec: Optional[tuple] = None,
        dep_rows: Optional[dict] = None,
    ) -> None:
        if vector is None or self._budget <= 0:
            return
        if kind != "count":
            result = copy.deepcopy(result)
        if repair_spec is None and repair_row is not None:
            # legacy single-row sugar (PR-13 call sites / tests): a
            # one-leaf "and" tree over the vector's only "v" element
            if (
                kind == "count"
                and self._repair_enabled
                and sum(1 for el in vector if el[0] == "v") == 1
            ):
                el = next(el for el in vector if el[0] == "v")
                repair_spec = ("and", ((el[2], el[3], repair_row),))
        if repair_spec is not None and not self._spec_admissible(
            kind, vector, repair_spec
        ):
            repair_spec = None
        e = _Entry(key, kind, index, text, result, vector, repair_spec,
                   clocks, dep_rows)
        if e.nbytes > self._budget:
            return  # a single over-budget entry would evict everything
        with self._mu:
            quota = self._quota_for_locked(index)
            if 0 < quota < e.nbytes:
                # a single entry bigger than the tenant's whole quota
                # can never be held within it — don't store it and then
                # immediately evict it (or someone else's entries)
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._unindex_locked(old)
            self._entries[key] = e
            self._index_locked(e)
            self._counters["stores"] += 1
            self._candidates.pop(key, None)
            self._evict_over_budget_locked()

    def _spec_admissible(
        self, kind: str, vector: tuple, repair_spec: tuple
    ) -> bool:
        """A repair spec is only usable when every leaf's (field, view)
        is represented by at least one local (int-token) "v" element:
        the patch reads host words through the view registry, which
        only local views live in. Purely-remote coordinator entries
        stay revalidate-only."""
        if kind != "count" or not self._repair_enabled:
            return False
        op, leaves = repair_spec
        if op not in ("and", "or") or not leaves:
            return False
        local = {
            (el[2], el[3])
            for el in vector
            if el[0] == "v" and isinstance(el[4], int)
        }
        return all((f, v) in local for f, v, _ in leaves)

    # -- internal indexing (all under self._mu) -----------------------------

    def _index_locked(self, e: _Entry) -> None:
        self._bytes += e.nbytes
        self._by_index[e.index] = self._by_index.get(e.index, 0) + e.nbytes
        self._by_text.setdefault((e.key[0], e.text), set()).add(e.key)
        for elem in e.vector:
            if elem[0] != "v":
                continue
            ident = elem[4]
            if isinstance(ident, int):  # local/in-process view token
                self._by_token.setdefault(ident, set()).add(e.key)
        if e.repair_spec is not None:
            for f, v, row in e.repair_spec[1]:
                rows = self._interest.setdefault((e.index, f, v), {})
                rows[row] = rows.get(row, 0) + 1

    def _unindex_locked(self, e: _Entry) -> None:
        self._bytes -= e.nbytes
        left = self._by_index.get(e.index, 0) - e.nbytes
        if left > 0:
            self._by_index[e.index] = left
        else:
            self._by_index.pop(e.index, None)
        tkey = (e.key[0], e.text)
        keys = self._by_text.get(tkey)
        if keys is not None:
            keys.discard(e.key)
            if not keys:
                self._by_text.pop(tkey, None)
        for elem in e.vector:
            if elem[0] != "v":
                continue
            ident = elem[4]
            if isinstance(ident, int):
                keys = self._by_token.get(ident)
                if keys is not None:
                    keys.discard(e.key)
                    if not keys:
                        self._by_token.pop(ident, None)
        if e.repair_spec is not None:
            for f, v, row in e.repair_spec[1]:
                ikey = (e.index, f, v)
                rows = self._interest.get(ikey)
                if rows is not None:
                    n = rows.get(row, 0) - 1
                    if n > 0:
                        rows[row] = n
                    else:
                        rows.pop(row, None)
                        if not rows:
                            self._interest.pop(ikey, None)

    def _drop_locked(self, key: tuple, evict: bool = False) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._unindex_locked(e)
            if evict:
                self._counters["evictions"] += 1

    def _quota_for_locked(self, index: str) -> int:
        q = self._tenant_quota.get(index)
        return q if q is not None else self._tenant_quota_default

    def _pinned_locked(self, e: _Entry) -> bool:
        return bool(self._pins) and (e.key[0], e.text) in self._pins

    def _evict_over_budget_locked(self) -> None:
        if self._tenant_quota or self._tenant_quota_default > 0:
            # tenant quotas first: over-quota owners shed their own LRU
            # entries before any in-quota entry is touched, and each
            # index is held to its quota even with global budget free
            self._evict_over_quota_locked()
        while self._bytes > self._budget and self._entries:
            # pinned (subscription) entries are skipped: evicting a
            # standing program's entry silently converts every push
            # into a full recompute. When ONLY pinned bytes remain the
            # loop stops over-budget rather than starve — the
            # subscription cap bounds how much can be pinned.
            key = next(
                (k for k, e in self._entries.items()
                 if not self._pinned_locked(e)),
                None,
            )
            if key is None:
                break
            self._drop_locked(key, evict=True)

    def _evict_over_quota_locked(self) -> None:
        for key, e in list(self._entries.items()):
            quota = self._quota_for_locked(e.index)
            if quota <= 0:
                continue
            if self._by_index.get(e.index, 0) <= quota:
                continue
            if self._pinned_locked(e):
                continue
            self._drop_locked(key, evict=True)
            self._counters["quota_evictions"] += 1
            self._quota_evictions_index[e.index] = (
                self._quota_evictions_index.get(e.index, 0) + 1
            )

    # -- invalidation funnels ----------------------------------------------

    def note_mutation(self, token: int, shard: int) -> None:
        """A fragment of the view owning `token` mutated (the same
        on_mutate hook that drives dirty-extent invalidation). Entries
        covering that (view, shard) whose result cannot be repaired drop
        eagerly; repairable Count entries stay for the repair window —
        revalidation keeps either choice exact."""
        self.note_mutations(token, (shard,))

    def note_mutations(self, token: int, shards: Iterable[int]) -> None:
        with self._mu:
            keys = self._by_token.get(token)
            if not keys:
                return
            dirty = set(shards)
            for key in list(keys):
                e = self._entries.get(key)
                if e is None:
                    continue
                covered = any(
                    elem[0] == "v"
                    and elem[4] == token
                    and dirty.intersection(elem[5])
                    for elem in e.vector
                )
                if not covered:
                    continue
                if e.repair_spec is None and e.dep_rows is None:
                    self._drop_locked(key)
                else:
                    # kept for the repair/re-key window, but no longer
                    # hit-likely: the admission discount must charge a
                    # possible recompute its full device bytes
                    e.maybe_stale = True

    def note_merges(self, token: int, merges: Iterable[Any]) -> None:
        """The merge barrier just applied staged deltas for fragments of
        the view owning `token` (View.sync_pending). Per covered entry:

        - repair-spec Counts whose touched leaves all live in the
          merging view patch in place under the lock (every leaf's
          base words come from the barrier's consistent capture);
        - repair-spec Counts with leaves in OTHER views become a
          deferred patch job: the other operands' premerge words are
          read outside this lock (fragment locks order below it — see
          Fragment.on_mutate) and the job re-validates the entry's
          whole vector before committing, dropping it on any doubt;
        - entries whose exact `dep_rows` are disjoint from the burst
          re-key forward without recompute (structural revalidation);
        - everything else covering a merged shard drops (stale and
          unrepairable).
        """
        if not merges:
            return
        by_shard = {m.shard: m for m in merges}
        jobs: List[dict] = []
        with self._mu:
            keys = self._by_token.get(token)
            if not keys:
                return
            for key in list(keys):
                e = self._entries.get(key)
                if e is None:
                    continue
                job = self._apply_merges_locked(e, token, by_shard)
                if job is not None:
                    jobs.append(job)
        for job in jobs:
            self._run_patch_job(job)

    def _apply_merges_locked(
        self, e: _Entry, token: int, by_shard: Dict[int, Any]
    ) -> Optional[dict]:
        """In-lock half of merge application. Returns None when fully
        handled (patched, re-keyed, or dropped) or a deferred patch job
        when other-view operand words must be read outside the lock.
        Deferred entries keep their OLD vector until the job commits,
        so they cannot serve a half-patched result — an exact-vector
        hit in the window simply misses."""
        new_vector = list(e.vector)
        changed = False
        count = e.result if e.kind == "count" else None
        units: List[dict] = []
        dep_rekeyed = False
        for i, elem in enumerate(e.vector):
            if elem[0] != "v" or elem[4] != token:
                continue
            field, view = elem[2], elem[3]
            shards, versions = elem[5], list(elem[6])
            spec_here = e.spec_rows(field, view)
            touched = False
            for pos, s in enumerate(shards):
                m = by_shard.get(s)
                if m is None:
                    continue
                if (
                    not self._repair_enabled
                    or not m.applied
                    or not m.clean
                    or versions[pos] != m.base_version
                ):
                    self._drop_locked(e.key)
                    return None
                burst = set(m.rows)
                hit_leaves = spec_here & burst
                if hit_leaves:
                    unit = self._patch_unit_locked(
                        e, elem, s, m, hit_leaves)
                    if unit is None:
                        self._drop_locked(e.key)
                        return None
                    if unit["reads"]:
                        units.append(unit)
                    else:
                        count += unit["delta"]
                        self._counters["repairs"] += 1
                        if len(e.repair_spec[1]) > 1:
                            self._counters["tree_repairs"] += 1
                elif spec_here:
                    # no leaf of the merging view touched: the count
                    # is unchanged and the entry re-keys forward
                    pass
                else:
                    dep = (e.dep_rows or {}).get((field, view))
                    if dep is None or dep & burst:
                        # unknown/total dependence, or a dependent row
                        # changed: the stored result may differ
                        self._drop_locked(e.key)
                        return None
                    dep_rekeyed = True
                versions[pos] = m.new_version
                touched = True
            if touched:
                new_vector[i] = elem[:6] + (tuple(versions),)
                changed = True
        if not changed:
            return None
        if units:
            # defer: commit vector + count together once the operand
            # reads land (outside this lock)
            return {
                "key": e.key,
                "expect": e.vector,
                "vector": tuple(new_vector),
                "base": count,
                "units": units,
                "leaves": len(e.repair_spec[1]),
            }
        e.vector = tuple(new_vector)
        # the clock moved with the burst: disarm the fast path until
        # the next exact-vector revalidation re-reads live clocks
        e.clocks = None
        # patched/re-keyed to the merged versions: hit-likely again
        e.maybe_stale = False
        if e.kind == "count":
            e.result = count
        if dep_rekeyed:
            self._counters["rekeys"] += 1
        return None

    def _patch_unit_locked(
        self, e: _Entry, elem: tuple, shard: int, m: Any, hit_leaves: set
    ) -> Optional[dict]:
        """Build one shard's patch: old/new word arrays for every leaf
        in the merging view (from the barrier's capture — one
        consistent snapshot at base version), plus read descriptors
        for leaves in OTHER views (resolved outside the lock). Returns
        None when the capture is missing (entry raced in after the
        barrier read interest)."""
        op, leaves = e.repair_spec
        field, view = elem[2], elem[3]
        widx: Set[int] = set()
        changed_pairs = []  # (old, new) full-row arrays, merging view
        same_view = []      # old full-row arrays, untouched leaves
        reads = []          # (field, view, row, expect_version)
        for f, v, row in leaves:
            if f == field and v == view:
                old = m.old_words.get(row)
                if old is None:
                    return None
                if row in hit_leaves:
                    wi, wv = m.word_delta(row)
                    new = old.copy()
                    new[wi] |= wv
                    widx.update(int(x) for x in wi)
                    changed_pairs.append((old, new))
                else:
                    same_view.append(old)
            else:
                ver = self._elem_version(e.vector, f, v, shard)
                if ver is None:
                    return None
                reads.append((f, v, row, ver))
        if not widx:
            return {"delta": 0, "reads": [], "shard": shard, "op": op,
                    "widx": (), "changed": (), "same": (), "index": e.index}
        wsel = np.array(sorted(widx), dtype=np.int64)
        changed = tuple((o[wsel], n[wsel]) for o, n in changed_pairs)
        same = tuple(o[wsel] for o in same_view)
        if reads:
            return {"delta": 0, "reads": reads, "shard": shard, "op": op,
                    "widx": wsel, "changed": changed, "same": same,
                    "index": e.index}
        delta = _tree_delta(op, changed, same, ())
        return {"delta": delta, "reads": [], "shard": shard, "op": op,
                "widx": wsel, "changed": changed, "same": same,
                "index": e.index}

    @staticmethod
    def _elem_version(
        vector: tuple, field: str, view: str, shard: int
    ) -> Optional[int]:
        """The version `vector` pins for (field, view, shard) on a
        LOCAL element, or None when no int-token element covers it."""
        for el in vector:
            if (
                el[0] == "v"
                and el[2] == field
                and el[3] == view
                and isinstance(el[4], int)
                and shard in el[5]
            ):
                return el[6][el[5].index(shard)]
        return None

    def _run_patch_job(self, job: dict) -> None:
        """Deferred half of a multi-view tree patch: read the other
        operands' premerge words (fragment locks only — the cache lock
        is NOT held), then commit count + vector iff the entry's vector
        is still exactly what the in-lock half saw. Any surprise —
        operand view gone, fragment version moved past the entry's
        element, vector changed underneath — drops the entry instead:
        revalidation semantics make dropping always safe."""
        total = 0
        ok = True
        for unit in job["units"]:
            other = []
            for f, v, row, expect_ver in unit["reads"]:
                words = self._read_operand(
                    job["key"], f, v, row, unit["shard"], expect_ver)
                if words is None:
                    ok = False
                    break
                other.append(words[unit["widx"]])
            if not ok:
                break
            total += _tree_delta(
                unit["op"], unit["changed"], unit["same"], tuple(other))
        with self._mu:
            e = self._entries.get(job["key"])
            if e is None:
                return
            if e.vector != job["expect"]:
                # a concurrent barrier moved the entry while the reads
                # were in flight: the reads may mix states — drop
                self._drop_locked(job["key"])
                return
            if not ok:
                self._drop_locked(job["key"])
                return
            e.vector = job["vector"]
            e.clocks = None
            e.maybe_stale = False
            e.result = job["base"] + total
            self._counters["repairs"] += len(job["units"])
            if job["leaves"] > 1:
                self._counters["tree_repairs"] += len(job["units"])

    def _read_operand(
        self, key: tuple, field: str, view: str, row: int, shard: int,
        expect_version: int,
    ) -> Optional[np.ndarray]:
        """Premerge words of one other-view operand, with a version
        double-read bracketing the word read: the words are usable only
        if the fragment provably sat at the entry's pinned version the
        whole time (a stage bumps the version BEFORE any content can
        move, so version-stable implies content-stable)."""
        with self._mu:
            ref = self._views.get(self._token_for(key, field, view))
        v = ref() if ref is not None else None
        if v is None:
            return None
        frag = v.fragments.get(shard)
        if frag is None:
            return None
        v0 = frag.version
        if v0 != expect_version:
            return None
        words = frag.premerge_row_words(row)
        if frag.version != v0:
            return None
        return words

    def _token_for(self, key: tuple, field: str, view: str) -> int:
        e = self._entries.get(key)
        if e is None:
            return -1
        for el in e.vector:
            if (
                el[0] == "v"
                and el[2] == field
                and el[3] == view
                and isinstance(el[4], int)
            ):
                return el[4]
        return -1

    def interest_rows(self, index: str, field: str, view: str) -> Set[int]:
        """Rows of (index, field, view) that repairable Count entries
        are watching — the merge barrier captures these rows' pre-merge
        words so note_merges can patch without re-reading operands.
        Fast empty path: one dict lookup under the lock."""
        with self._mu:
            rows = self._interest.get((index, field, view))
            return set(rows) if rows else set()

    # -- pins / view registry (coherence plane) ------------------------------

    def pin_text(self, scope: Hashable, text: str) -> None:
        """Pin every entry (current and future) stored for
        (scope, text): eviction skips it. Refcounted — subscriptions
        over the same program share the pin."""
        with self._mu:
            k = (scope, text)
            self._pins[k] = self._pins.get(k, 0) + 1

    def unpin_text(self, scope: Hashable, text: str) -> None:
        with self._mu:
            k = (scope, text)
            n = self._pins.get(k, 0) - 1
            if n > 0:
                self._pins[k] = n
            else:
                self._pins.pop(k, None)

    def register_view(self, view: Any) -> None:
        """Make `view` resolvable by its `_stack_token` for deferred
        tree-patch operand reads (View.open calls this; drop_view
        removes the registration with the token's entries)."""
        with self._mu:
            self._views[view._stack_token] = weakref.ref(view)

    def repair_likely(self, scope: Optional[Hashable], text: str) -> bool:
        """Whether a maybe-stale entry for (scope, text) is expected to
        come back via repair or re-key rather than recompute — the
        admission estimator's middle tier (sched/cost.py): such a
        repeat costs host microseconds, not device bytes, but charging
        it fully-free would let a recompute bypass the byte budget when
        the repair window closes unluckily."""
        if scope is None:
            return False
        with self._mu:
            keys = self._by_text.get((scope, text))
            if not keys:
                return False
            return any(
                e.repair_spec is not None or e.dep_rows is not None
                for k in keys
                if (e := self._entries.get(k)) is not None
            )

    # -- GC ----------------------------------------------------------------

    def drop_view(self, token: int) -> None:
        """A View closed (field/index delete, fragment drop): entries
        whose vector references it must not outlive it."""
        with self._mu:
            for key in list(self._by_token.get(token, ())):
                self._drop_locked(key)
            self._views.pop(token, None)

    def drop_index(self, index: str) -> None:
        """Label GC on index delete (NodeServer.drop_index_telemetry):
        the per-index byte attribution, the tenant eviction ledger and
        every entry must go with the index. (The quota OVERRIDE stays —
        operator config re-applies if the index is recreated.)"""
        with self._mu:
            for key, e in list(self._entries.items()):
                if e.index == index:
                    self._drop_locked(key)
            self._quota_evictions_index.pop(index, None)

    def drop_scope(self, scope: Hashable) -> None:
        """Drop every entry keyed under one Index's cache scope (rank
        cache recalculation: TopN order can change with no version
        bump)."""
        with self._mu:
            for key in list(self._entries):
                if key[0] == scope:
                    self._drop_locked(key)

    def _clear_locked(self) -> None:
        self._entries.clear()
        self._by_token.clear()
        self._by_index.clear()
        self._interest.clear()
        self._by_text.clear()
        self._candidates.clear()
        self._bytes = 0

    def clear(self) -> None:
        """Drop every entry (counters keep accumulating)."""
        with self._mu:
            self._clear_locked()

    def reset(self) -> None:
        """clear() plus counter reset and tenant-quota reset to
        unlimited (test isolation)."""
        with self._mu:
            self._clear_locked()
            for k in self._counters:
                self._counters[k] = 0
            self._tenant_quota_default = 0
            self._tenant_quota = {}
            self._quota_evictions_index = {}
            self._pins = {}
            self._views = {}

    # -- introspection ------------------------------------------------------

    def has_text(self, scope: Optional[Hashable], text: str) -> bool:
        """Whether a HIT-LIKELY entry is stored for (scope, text) — the
        admission cost estimator's probe (sched/cost.py). Cheap by
        design (no version walk), but entries that observed a covered
        mutation since they last proved fresh are excluded: a
        maybe-stale entry's repeat may recompute at full cost, and
        admitting that byte-free would let it bypass the byte budget."""
        if scope is None:
            return False
        with self._mu:
            keys = self._by_text.get((scope, text))
            if not keys:
                return False
            return any(
                not e.maybe_stale
                for k in keys
                if (e := self._entries.get(k)) is not None
            )

    def stats_snapshot(self) -> Dict[str, Any]:
        """cache.* gauge values (NodeServer.publish_cache_gauges) plus
        the per-index byte attribution."""
        with self._mu:
            snap: Dict[str, Any] = dict(self._counters)
            snap["resident_bytes"] = self._bytes
            snap["entries"] = len(self._entries)
            snap["by_index"] = dict(self._by_index)
            snap["quota_evictions_by_index"] = dict(
                self._quota_evictions_index
            )
            return snap


RESULT_CACHE = ResultCache()
