"""Versioned result cache with incremental count repair.

The canonical Pilosa workload (PAPER.md §L2) is a dashboard fleet
re-issuing the same segmentation queries every few seconds; every layer
below already speaks fragment versions (version-salted extent keys,
version-salted mesh tally bundles, the merge barrier's per-fragment word
deltas). This module lifts that one level: it caches query RESULTS —
Count scalars, TopN tallies, GroupBy matrices — keyed on the canonical
query text plus the exact fragment-version vector the plan read, with
two freshness paths:

- **revalidation**: a repeat query re-collects the current version
  vector (lock-free monotonic reads — every mutation funnel bumps
  `Fragment.version`); an unchanged vector means the stored result is
  bit-identical to what a recompute would produce, so it is served from
  host memory with zero compiled dispatches and zero device reads.
- **incremental repair** (Counts over a single row): the merge
  barrier's `FragMerge.word_delta` is exactly the information needed to
  patch a cached popcount without re-staging any operand —
  `count(new) = count(old) + popcount(delta & ~old_words)` for a
  set-only staged burst, where `old_words` is the row's host words at
  the burst's base version (captured by the barrier BEFORE the delta
  layer parks, core/merge.py). Clears, mutex writes and version gaps
  make the delta non-monotone; those entries fall back to recompute.

Scoping: one process-global RESULT_CACHE serves every in-process node
(the multi-node test harnesses run several NodeServers in one process).
Keys carry the owning Index's `_cache_scope` token and version-vector
elements carry per-View `_stack_token`s, so two nodes holding
same-named indexes can never serve each other's entries — version
counters are per-fragment-instance and would otherwise collide.

Invalidation rides the existing funnels: `Fragment.on_mutate` (via the
owning View) reports the mutated shard — non-repairable entries
covering it drop eagerly, repairable Count entries stay for the repair
window; `View.sync_pending` reports the barrier's merges — Count
entries patch in place (or re-key when the burst missed their row),
everything else stale-drops. Entries a hook never reaches are still
safe: revalidation makes a stale entry unservable (versions only ever
grow), it just waits for LRU.
"""

from __future__ import annotations

import copy
from collections import OrderedDict
from typing import Any, Dict, Hashable, Iterable, Optional, Set, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.utils.race import race_checked

# Default LRU byte budget ([cache] result-cache-mb knob; 0 disables the
# cache outright — get/put become no-ops).
DEFAULT_BUDGET_BYTES = 64 << 20

# Keys executed through an RPC-assembled version vector (HTTP fan-out
# coordinators) only start caching on their SECOND sighting: collecting
# remote versions costs a round trip per peer, and paying it for
# one-off queries would tax every cold query to speed up none.
_CANDIDATE_CAP = 1024

_UNSET = object()


def _popcount(words: np.ndarray) -> int:
    """Exact popcount of a uint32 word array (small: delta words only)."""
    if not len(words):
        return 0
    return int(
        np.unpackbits(np.ascontiguousarray(words).view(np.uint8)).sum()
    )


def _result_nbytes(kind: str, result: Any) -> int:
    if kind == "count":
        return 32
    # per-element rates sized to the real Python object graphs (a
    # GroupCount carries a FieldRow list; a Pair is a small dataclass):
    # a high-cardinality GroupBy must charge the budget roughly what it
    # costs in RSS, or a 64 MB knob would admit hundreds of real MB
    per = 384 if kind == "groupby" else 112
    try:
        return 64 + per * len(result)
    except TypeError:
        return 256


def _vector_nbytes(vector: tuple) -> int:
    n = 64
    for elem in vector:
        n += 48
        if elem[0] == "v":
            n += 16 * len(elem[5])
    return n


class _Entry:
    """One cached result.

    `vector` is a tuple of elements, one per (node, field, view) the
    query read:

      ("v", node, field, view, ident, shards, versions)
          ident = the View's `_stack_token` (local / in-process mesh
          member) or (boot_id, token) for a remote node's view —
          instance identity, so delete/recreate or a peer restart can
          never alias an old entry back to life;
      ("m", node, field, view)
          the field/view did not exist ("" view = field missing); its
          materialization changes the element shape, forcing a miss.

    `repair_row` is set only for Count over a single plain Row (the
    vector then has exactly one "v" element): the row id whose merged
    word delta can patch the cached scalar in place."""

    __slots__ = (
        "key", "kind", "index", "text", "result", "vector", "repair_row",
        "clocks", "maybe_stale", "nbytes",
    )

    def __init__(
        self,
        key: tuple,
        kind: str,
        index: str,
        text: str,
        result: Any,
        vector: tuple,
        repair_row: Optional[int],
        clocks: Optional[tuple] = None,
    ) -> None:
        self.key = key
        self.kind = kind
        self.index = index
        self.text = text
        self.result = result
        self.vector = vector
        self.repair_row = repair_row
        # per-view mutation-clock vector (View.mutation_clock) read
        # BEFORE the version vector: clock-equal implies version-equal,
        # so warm repeats revalidate on one integer per view instead of
        # walking the shard axis. None = fall back to the exact vector.
        self.clocks = clocks
        # a covered mutation was observed since the entry last proved
        # fresh (store / hit / in-place repair). Drives the admission
        # cost discount only — a maybe-stale entry must not admit a
        # recompute byte-free (sched/cost.py); serving correctness
        # never reads it.
        self.maybe_stale = False
        self.nbytes = (
            len(text)
            + _result_nbytes(kind, result)
            + _vector_nbytes(vector)
        )


@race_checked(exclude=(
    # [cache] knobs: written by NodeServer construction/configure, read
    # lock-free on the hot lookup paths — a racy read sees either the
    # old or the new setting, both valid configurations (GIL-atomic
    # int/bool reads; entries themselves stay fully lock-guarded)
    "_budget",
    "_repair_enabled",
))
class ResultCache:
    """LRU byte-budgeted store of versioned query results (one
    process-global instance, RESULT_CACHE, like core/devcache.py)."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES) -> None:
        self._mu = TrackedLock("resultcache.mu")
        self._budget = int(budget_bytes)
        self._repair_enabled = True
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        # view token -> keys whose vector covers it (invalidation/repair)
        self._by_token: Dict[int, Set[tuple]] = {}
        # index name -> resident bytes (per-tenant attribution; feeds the
        # cache.resident_bytes{index} gauge and quota work)
        self._by_index: Dict[str, int] = {}
        # (index, field, view) -> row -> refcount of repairable Count
        # entries interested in that row's pre-merge words (the merge
        # barrier's old-words capture hook, core/merge.py)
        self._interest: Dict[tuple, Dict[int, int]] = {}
        # (scope, text) -> live entry keys (admission cost discount)
        self._by_text: Dict[tuple, Set[tuple]] = {}
        # keys seen once but not yet cached (RPC-vector gating)
        self._candidates: "OrderedDict[tuple, bool]" = OrderedDict()
        # per-index (tenant) byte quotas ([tenants] section; 0 / absent
        # = unlimited): an index is held to its quota even when the
        # global budget has room, and under global pressure over-quota
        # owners evict first — tenant A's microsecond-serve entries
        # survive tenant B's flood
        self._tenant_quota_default = 0
        self._tenant_quota: Dict[str, int] = {}
        self._quota_evictions_index: Dict[str, int] = {}
        self._counters: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "revalidations": 0,
            "repairs": 0,
            "evictions": 0,
            "stores": 0,
            "quota_evictions": 0,
        }

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        budget_bytes: Any = _UNSET,
        repair: Any = _UNSET,
        tenant_default_bytes: Any = _UNSET,
        tenant_overrides: Any = _UNSET,
    ) -> None:
        """Install the server's [cache] knobs (cli/config.py ->
        server/node.py) and the [tenants] per-index cache quotas.
        Process-global like the [hbm] knobs: all in-process nodes share
        one store (entries stay node-scoped via the index/view tokens in
        their keys)."""
        with self._mu:
            if budget_bytes is not _UNSET:
                self._budget = int(budget_bytes)
            if repair is not _UNSET:
                self._repair_enabled = bool(repair)
            if tenant_default_bytes is not _UNSET:
                self._tenant_quota_default = max(0, int(tenant_default_bytes))
            if tenant_overrides is not _UNSET:
                self._tenant_quota = {
                    k: max(0, int(v))
                    for k, v in (tenant_overrides or {}).items()
                }
            self._evict_over_budget_locked()

    @property
    def budget_bytes(self) -> int:
        return self._budget

    @property
    def repair_enabled(self) -> bool:
        return self._repair_enabled

    # -- lookup / store -----------------------------------------------------

    def get(
        self, key: tuple, vector: Optional[tuple], recount: bool = True
    ) -> Tuple[bool, Any]:
        """(found, result). A hit requires the entry's stored vector to
        EQUAL the caller's freshly collected one — identical fragment
        versions mean identical content, so the stored result is what a
        recompute would return. `recount=False` suppresses the miss
        counter (the repair retry re-gets after running the barrier)."""
        if vector is None or self._budget <= 0:
            return False, None
        with self._mu:
            e = self._entries.get(key)
            if e is not None and e.vector == vector:
                self._entries.move_to_end(key)
                self._counters["hits"] += 1
                self._counters["revalidations"] += 1
                e.maybe_stale = False
                result = e.result
                kind = e.kind
            else:
                if recount:
                    self._counters["misses"] += 1
                return False, None
        if kind == "count":
            return True, result
        return True, copy.deepcopy(result)

    def get_by_clock(
        self, key: tuple, clocks: Optional[tuple]
    ) -> Tuple[bool, Any]:
        """(found, result): the O(#views) fast path — serve when the
        caller's freshly read per-view mutation clocks equal the
        entry's. Sound because every fragment-version bump also bumps
        its view's clock (and clocks were read BEFORE the entry's
        vector at store/refresh time): clock-equal ⇒ zero mutation
        events since ⇒ version-vector-equal. Misses are silent — the
        caller falls back to the exact vector path, which counts."""
        if clocks is None or self._budget <= 0:
            return False, None
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.clocks is None or e.clocks != clocks:
                return False, None
            self._entries.move_to_end(key)
            self._counters["hits"] += 1
            self._counters["revalidations"] += 1
            e.maybe_stale = False
            result = e.result
            kind = e.kind
        if kind == "count":
            return True, result
        return True, copy.deepcopy(result)

    def refresh_clocks(self, key: tuple, clocks: Optional[tuple]) -> None:
        """Arm the clock fast path after a successful exact-vector
        revalidation. `clocks` MUST have been read before the vector
        the caller just matched — a write landing in between then keeps
        the fast path disarmed (live clock moved past), never wrong."""
        if clocks is None:
            return
        with self._mu:
            e = self._entries.get(key)
            if e is not None:
                e.clocks = clocks

    def count_miss(self) -> None:
        """Book one lookup that concluded a miss. The executor defers
        this until the repair retry has also failed, so one logical
        lookup never records both a miss and a hit (a repaired serve
        would otherwise read as cacheHitRate 0.5 on a 100%-served
        dashboard)."""
        with self._mu:
            self._counters["misses"] += 1

    def repairable(self, key: tuple) -> bool:
        """Whether a miss on `key` is worth a repair attempt: a live
        Count entry with a repair row, and repair enabled. The caller
        then runs the read barrier (which fires note_merges) and
        re-gets."""
        if not self._repair_enabled:
            return False
        with self._mu:
            e = self._entries.get(key)
            return e is not None and e.repair_row is not None

    def note_candidate(self, key: tuple) -> bool:
        """Record a sighting of an RPC-vector key; True when the key was
        already seen (worth paying the version round trips now)."""
        with self._mu:
            if key in self._entries:
                return True
            if key in self._candidates:
                self._candidates.move_to_end(key)
                return True
            self._candidates[key] = True
            while len(self._candidates) > _CANDIDATE_CAP:
                self._candidates.popitem(last=False)
            return False

    def put(
        self,
        key: tuple,
        kind: str,
        index: str,
        text: str,
        result: Any,
        vector: tuple,
        repair_row: Optional[int] = None,
        clocks: Optional[tuple] = None,
    ) -> None:
        if vector is None or self._budget <= 0:
            return
        if kind != "count":
            result = copy.deepcopy(result)
        if repair_row is not None and (
            kind != "count"
            or not self._repair_enabled
            or sum(1 for el in vector if el[0] == "v") != 1
        ):
            repair_row = None
        e = _Entry(key, kind, index, text, result, vector, repair_row, clocks)
        if e.nbytes > self._budget:
            return  # a single over-budget entry would evict everything
        with self._mu:
            quota = self._quota_for_locked(index)
            if 0 < quota < e.nbytes:
                # a single entry bigger than the tenant's whole quota
                # can never be held within it — don't store it and then
                # immediately evict it (or someone else's entries)
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._unindex_locked(old)
            self._entries[key] = e
            self._index_locked(e)
            self._counters["stores"] += 1
            self._candidates.pop(key, None)
            self._evict_over_budget_locked()

    # -- internal indexing (all under self._mu) -----------------------------

    def _index_locked(self, e: _Entry) -> None:
        self._bytes += e.nbytes
        self._by_index[e.index] = self._by_index.get(e.index, 0) + e.nbytes
        self._by_text.setdefault((e.key[0], e.text), set()).add(e.key)
        for elem in e.vector:
            if elem[0] != "v":
                continue
            ident = elem[4]
            if isinstance(ident, int):  # local/in-process view token
                self._by_token.setdefault(ident, set()).add(e.key)
        if e.repair_row is not None:
            elem = next(el for el in e.vector if el[0] == "v")
            ikey = (e.index, elem[2], elem[3])
            rows = self._interest.setdefault(ikey, {})
            rows[e.repair_row] = rows.get(e.repair_row, 0) + 1

    def _unindex_locked(self, e: _Entry) -> None:
        self._bytes -= e.nbytes
        left = self._by_index.get(e.index, 0) - e.nbytes
        if left > 0:
            self._by_index[e.index] = left
        else:
            self._by_index.pop(e.index, None)
        tkey = (e.key[0], e.text)
        keys = self._by_text.get(tkey)
        if keys is not None:
            keys.discard(e.key)
            if not keys:
                self._by_text.pop(tkey, None)
        for elem in e.vector:
            if elem[0] != "v":
                continue
            ident = elem[4]
            if isinstance(ident, int):
                keys = self._by_token.get(ident)
                if keys is not None:
                    keys.discard(e.key)
                    if not keys:
                        self._by_token.pop(ident, None)
        if e.repair_row is not None:
            elem = next(el for el in e.vector if el[0] == "v")
            ikey = (e.index, elem[2], elem[3])
            rows = self._interest.get(ikey)
            if rows is not None:
                n = rows.get(e.repair_row, 0) - 1
                if n > 0:
                    rows[e.repair_row] = n
                else:
                    rows.pop(e.repair_row, None)
                    if not rows:
                        self._interest.pop(ikey, None)

    def _drop_locked(self, key: tuple, evict: bool = False) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._unindex_locked(e)
            if evict:
                self._counters["evictions"] += 1

    def _quota_for_locked(self, index: str) -> int:
        q = self._tenant_quota.get(index)
        return q if q is not None else self._tenant_quota_default

    def _evict_over_budget_locked(self) -> None:
        if self._tenant_quota or self._tenant_quota_default > 0:
            # tenant quotas first: over-quota owners shed their own LRU
            # entries before any in-quota entry is touched, and each
            # index is held to its quota even with global budget free
            self._evict_over_quota_locked()
        while self._bytes > self._budget and self._entries:
            key = next(iter(self._entries))
            self._drop_locked(key, evict=True)

    def _evict_over_quota_locked(self) -> None:
        for key, e in list(self._entries.items()):
            quota = self._quota_for_locked(e.index)
            if quota <= 0:
                continue
            if self._by_index.get(e.index, 0) <= quota:
                continue
            self._drop_locked(key, evict=True)
            self._counters["quota_evictions"] += 1
            self._quota_evictions_index[e.index] = (
                self._quota_evictions_index.get(e.index, 0) + 1
            )

    # -- invalidation funnels ----------------------------------------------

    def note_mutation(self, token: int, shard: int) -> None:
        """A fragment of the view owning `token` mutated (the same
        on_mutate hook that drives dirty-extent invalidation). Entries
        covering that (view, shard) whose result cannot be repaired drop
        eagerly; repairable Count entries stay for the repair window —
        revalidation keeps either choice exact."""
        self.note_mutations(token, (shard,))

    def note_mutations(self, token: int, shards: Iterable[int]) -> None:
        with self._mu:
            keys = self._by_token.get(token)
            if not keys:
                return
            dirty = set(shards)
            for key in list(keys):
                e = self._entries.get(key)
                if e is None:
                    continue
                covered = any(
                    elem[0] == "v"
                    and elem[4] == token
                    and dirty.intersection(elem[5])
                    for elem in e.vector
                )
                if not covered:
                    continue
                if e.repair_row is None:
                    self._drop_locked(key)
                else:
                    # kept for the repair window, but no longer
                    # hit-likely: the admission discount must charge a
                    # possible recompute its full device bytes
                    e.maybe_stale = True

    def note_merges(self, token: int, merges: Iterable[Any]) -> None:
        """The merge barrier just applied staged deltas for fragments of
        the view owning `token` (View.sync_pending). Patch every covered
        repairable Count entry in place — count(new) = count(old) +
        popcount(delta & ~old_words) when the burst touched its row,
        version re-key alone when it did not — and drop everything else
        covering a merged shard (their results are stale and
        unrepairable)."""
        if not merges:
            return
        by_shard = {m.shard: m for m in merges}
        with self._mu:
            keys = self._by_token.get(token)
            if not keys:
                return
            for key in list(keys):
                e = self._entries.get(key)
                if e is None:
                    continue
                self._apply_merges_locked(e, token, by_shard)

    def _apply_merges_locked(
        self, e: _Entry, token: int, by_shard: Dict[int, Any]
    ) -> None:
        new_vector = list(e.vector)
        changed = False
        count = e.result if e.kind == "count" else None
        for i, elem in enumerate(e.vector):
            if elem[0] != "v" or elem[4] != token:
                continue
            shards, versions = elem[5], list(elem[6])
            touched = False
            for pos, s in enumerate(shards):
                m = by_shard.get(s)
                if m is None:
                    continue
                if (
                    e.repair_row is None
                    or not self._repair_enabled
                    or not m.applied
                    or not m.clean
                    or versions[pos] != m.base_version
                ):
                    self._drop_locked(e.key)
                    return
                if e.repair_row in m.rows:
                    old = m.old_words.get(e.repair_row)
                    if old is None:
                        # the barrier had no interest registered when it
                        # captured (entry raced in): unrepairable
                        self._drop_locked(e.key)
                        return
                    widx, wvals = m.word_delta(e.repair_row)
                    count += _popcount(
                        np.bitwise_and(wvals, np.bitwise_not(old[widx]))
                    )
                    self._counters["repairs"] += 1
                # row untouched by the burst: the count is unchanged and
                # the entry just re-keys forward to the merged version
                versions[pos] = m.new_version
                touched = True
            if touched:
                new_vector[i] = elem[:6] + (tuple(versions),)
                changed = True
        if changed:
            e.vector = tuple(new_vector)
            # the clock moved with the burst: disarm the fast path until
            # the next exact-vector revalidation re-reads live clocks
            e.clocks = None
            # patched to the merged versions: hit-likely again
            e.maybe_stale = False
            if e.kind == "count":
                e.result = count

    def interest_rows(self, index: str, field: str, view: str) -> Set[int]:
        """Rows of (index, field, view) that repairable Count entries
        are watching — the merge barrier captures these rows' pre-merge
        words so note_merges can patch without re-reading operands.
        Fast empty path: one dict lookup under the lock."""
        with self._mu:
            rows = self._interest.get((index, field, view))
            return set(rows) if rows else set()

    # -- GC ----------------------------------------------------------------

    def drop_view(self, token: int) -> None:
        """A View closed (field/index delete, fragment drop): entries
        whose vector references it must not outlive it."""
        with self._mu:
            for key in list(self._by_token.get(token, ())):
                self._drop_locked(key)

    def drop_index(self, index: str) -> None:
        """Label GC on index delete (NodeServer.drop_index_telemetry):
        the per-index byte attribution, the tenant eviction ledger and
        every entry must go with the index. (The quota OVERRIDE stays —
        operator config re-applies if the index is recreated.)"""
        with self._mu:
            for key, e in list(self._entries.items()):
                if e.index == index:
                    self._drop_locked(key)
            self._quota_evictions_index.pop(index, None)

    def drop_scope(self, scope: Hashable) -> None:
        """Drop every entry keyed under one Index's cache scope (rank
        cache recalculation: TopN order can change with no version
        bump)."""
        with self._mu:
            for key in list(self._entries):
                if key[0] == scope:
                    self._drop_locked(key)

    def _clear_locked(self) -> None:
        self._entries.clear()
        self._by_token.clear()
        self._by_index.clear()
        self._interest.clear()
        self._by_text.clear()
        self._candidates.clear()
        self._bytes = 0

    def clear(self) -> None:
        """Drop every entry (counters keep accumulating)."""
        with self._mu:
            self._clear_locked()

    def reset(self) -> None:
        """clear() plus counter reset and tenant-quota reset to
        unlimited (test isolation)."""
        with self._mu:
            self._clear_locked()
            for k in self._counters:
                self._counters[k] = 0
            self._tenant_quota_default = 0
            self._tenant_quota = {}
            self._quota_evictions_index = {}

    # -- introspection ------------------------------------------------------

    def has_text(self, scope: Optional[Hashable], text: str) -> bool:
        """Whether a HIT-LIKELY entry is stored for (scope, text) — the
        admission cost estimator's probe (sched/cost.py). Cheap by
        design (no version walk), but entries that observed a covered
        mutation since they last proved fresh are excluded: a
        maybe-stale entry's repeat may recompute at full cost, and
        admitting that byte-free would let it bypass the byte budget."""
        if scope is None:
            return False
        with self._mu:
            keys = self._by_text.get((scope, text))
            if not keys:
                return False
            return any(
                not e.maybe_stale
                for k in keys
                if (e := self._entries.get(k)) is not None
            )

    def stats_snapshot(self) -> Dict[str, Any]:
        """cache.* gauge values (NodeServer.publish_cache_gauges) plus
        the per-index byte attribution."""
        with self._mu:
            snap: Dict[str, Any] = dict(self._counters)
            snap["resident_bytes"] = self._bytes
            snap["entries"] = len(self._entries)
            snap["by_index"] = dict(self._by_index)
            snap["quota_evictions_by_index"] = dict(
                self._quota_evictions_index
            )
            return snap


RESULT_CACHE = ResultCache()
