"""View: groups fragments by shard for one "view" of a field.

Reference: /root/reference/view.go — view names are `standard`, time-quantum
views (`standard_2019`, `standard_201907`, ...), and `bsig_<field>` for BSI
groups (view.go:37-41)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock, TrackedRLock
from pilosa_tpu.coherence import hub as coherence_hub
from pilosa_tpu.core import wal as walmod
from pilosa_tpu.core.devcache import DEVICE_CACHE, new_owner_token
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.resultcache import RESULT_CACHE
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


class View:
    def __init__(
        self,
        name: str,
        index: str,
        field: str,
        path: Optional[str],
        *,
        mutex: bool = False,
        max_op_n: int = 10_000,
        cache_type: str = "ranked",
        cache_size: int = 50_000,
    ):
        self.name = name
        self.index = index
        self.field = field
        self.path = path  # directory holding fragments/; None => in-memory
        self.mutex = mutex
        self.max_op_n = max_op_n
        self.cache_type = cache_type
        self.cache_size = cache_size
        self._mu = TrackedRLock("view.mu")
        self.fragments: Dict[int, Fragment] = {}
        # owner token for cross-shard row stacks in the global device cache
        self._stack_token = new_owner_token()
        # view-level mutation clock (result cache fast path): bumped on
        # EVERY mutation event that bumps a fragment version — the
        # on_mutate funnel and the bulk stage router — so clock-equal
        # implies every fragment version in this view is unchanged. The
        # cache revalidates warm repeats against this one integer per
        # view instead of walking the whole shard axis; a clock mismatch
        # falls back to the exact per-shard vector (a write to a
        # DISJOINT shard subset must not kill covering entries).
        # ORDERING CONTRACT: the clock bumps AFTER the version bump(s),
        # before the mutation call returns. A reader overlapping an
        # IN-FLIGHT write may therefore still fast-path the pre-write
        # result — the same partial-visibility window any query racing
        # a bulk import already has — but once the write returns, every
        # later lookup sees the new clock. Trailing (not leading) is
        # load-bearing: it guarantees a clock read always corresponds
        # to a state no NEWER than any vector read after it, which is
        # what makes arming entries with (clock, vector) pairs sound —
        # a leading bump could arm a pre-write vector under the
        # post-write clock and serve stale forever.
        # Dedicated leaf lock: bumps happen under fragment locks, and
        # view._mu is taken BEFORE fragment locks elsewhere (fragment
        # creation) — a lost += under concurrency could freeze the clock
        # across a real mutation, which revalidation soundness forbids.
        self._clock_mu = TrackedLock("view.clock_mu")
        self.mutation_clock = 0
        # shards with staged writes whose covering stack extents were NOT
        # invalidated at stage time (they are version-keyed, so they can
        # never be served stale): the merge barrier's reconciliation
        # either patches them in place to the merged version or drops
        # them (sync_pending -> _reconcile_extents)
        self._dirty_staged: set = set()
        # tiered storage (pilosa_tpu/tier/): when set, shards missing
        # from `fragments` may be COLD — demoted to the object store —
        # and every lookup that would treat absence as emptiness must
        # consult the resolver first (resolve() hydrates on demand,
        # single-flight). None = tier disabled, zero overhead.
        self.cold_resolver = None

    def open(self) -> "View":
        """Load existing fragments from disk (view.go:120 openFragments)."""
        if self.path is not None:
            frag_dir = os.path.join(self.path, "fragments")
            if os.path.isdir(frag_dir):
                for fn in sorted(os.listdir(frag_dir)):
                    if fn.endswith(".snap") or fn.endswith(".wal"):
                        shard_s = fn.rsplit(".", 1)[0]
                        if shard_s.isdigit():
                            self.fragment(int(shard_s))
        # coherence plane: register for deferred tree-repair operand reads
        # (core/resultcache.py resolves tokens back to live views through
        # this weak registry; a no-op when repair never defers)
        RESULT_CACHE.register_view(self)
        return self

    def close(self) -> None:
        with self._mu:
            for frag in self.fragments.values():
                frag.close()
            # drop the view-level device stacks (row/plane stacks, TopN
            # tally bundles — all keyed under _stack_token): a deleted
            # index's arrays must leave the device ledger, and their
            # per-index attribution must not resurrect the label after
            # telemetry GC
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            RESULT_CACHE.drop_view(self._stack_token)
            self._dirty_staged.clear()
        # outside the view lock: publishers ship drop tombstones so leased
        # mirrors forget this view instead of holding its last versions
        # forever (monotone merge would otherwise mask the deletion)
        coherence_hub.note_view_drop(self)

    def _fragment_path(self, shard: int) -> Optional[str]:
        if self.path is None:
            return None
        return os.path.join(self.path, "fragments", str(shard))

    def fragment(self, shard: int) -> Fragment:
        """Get-or-create the fragment for a shard (view.go:263
        CreateFragmentIfNotExists)."""
        with self._mu:
            frag = self.fragments.get(shard)
        if frag is not None:
            return frag
        res = self.cold_resolver
        if res is not None:
            # the shard may be demoted: creating a fresh empty fragment
            # here would SHADOW the stored snapshot and lose it on the
            # next hydrate — resolve (and possibly fetch) outside the
            # view lock, since hydration blocks on store I/O
            frag = res.resolve(self, shard)
            if frag is not None:
                return frag
        with self._mu:
            frag = self.fragments.get(shard)
            if frag is None:
                frag = Fragment(
                    self._fragment_path(shard),
                    self.index,
                    self.field,
                    self.name,
                    shard,
                    mutex=self.mutex,
                    max_op_n=self.max_op_n,
                    cache_type=self.cache_type,
                    cache_size=self.cache_size,
                ).open()
                # dirty-extent invalidation: a write reports WHICH shard
                # changed, and only the stack entries whose extent span
                # covers it are dropped (stale version keys would miss
                # anyway; this frees exactly the stale HBM immediately
                # instead of churning the whole owner or waiting on LRU)
                frag.on_mutate = lambda s=shard: self._on_fragment_mutate(s)
                self.fragments[shard] = frag
            return frag

    def _on_fragment_mutate(self, shard: int) -> None:
        """The per-mutation funnel (Fragment.on_mutate): dirty-extent
        device invalidation plus the result-cache notification — cached
        results covering the mutated (view, shard) drop eagerly unless
        they are Count entries awaiting the merge barrier's in-place
        repair (core/resultcache.py)."""
        with self._clock_mu:
            self.mutation_clock += 1
        DEVICE_CACHE.invalidate_owner_shard(self._stack_token, shard)
        RESULT_CACHE.note_mutation(self._stack_token, shard)
        coherence_hub.note_view_mutation(self, (shard,))
        res = self.cold_resolver
        if res is not None:
            # writes count as activity for the tier's LRU demote clock —
            # a write-hot fragment must never look idle to the ticker
            res.touch_many(self, (shard,))

    def fragment_if_exists(self, shard: int) -> Optional[Fragment]:
        frag = self.fragments.get(shard)
        if frag is not None:
            return frag
        res = self.cold_resolver
        if res is not None:
            # "exists" includes cold: a demoted fragment still HAS the
            # data (in the object store) — hydrate rather than report
            # absence, which reads as zeros to every caller
            return res.resolve(self, shard)
        return None

    def delete_fragment(self, shard: int) -> bool:
        """Drop one shard's fragment: close it, delete its on-disk files
        and free its device-cache residency (the post-resize holder
        cleaner's unit of work, reference holder.go:1126)."""
        with self._mu:
            frag = self.fragments.pop(shard, None)
            if frag is None:
                return False
            frag.close()  # also frees the fragment's device-cache residency
            for p in (frag.snap_path, frag.wal_path, frag.cache_path):
                if p is not None:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            RESULT_CACHE.drop_view(self._stack_token)
        # fragment gone: the publisher's flush finds no fragment for this
        # shard and demotes the bump to a drop tombstone, so leased mirrors
        # never pin the deleted shard's last version as live
        coherence_hub.note_view_mutation(self, (shard,))
        return True

    def available_shards(self) -> List[int]:
        with self._mu:
            shards = set(self.fragments)
        res = self.cold_resolver
        if res is not None:
            # cold shards are still AVAILABLE — they hydrate on access;
            # omitting them would silently shrink every query's shard
            # span the moment a fragment demotes
            shards |= res.cold_shards(self)
        return sorted(shards)

    def evict_fragment(self, shard: int, end_capture_tag=None) -> bool:
        """Tier demotion eviction: detach + close + delete the local
        files of a shard whose snapshot object is already DURABLE in the
        tier store. Unlike delete_fragment the data still exists (cold),
        so only this shard's device entries drop — version-keyed stack
        extents and cached results covering OTHER shards stay exact, and
        the result cache is untouched (content is unchanged, so serving
        a covering cached result remains correct)."""
        with self._mu:
            frag = self.fragments.pop(shard, None)
        if frag is None:
            return False
        if end_capture_tag is not None:
            # ends the demote capture AFTER detach: the lifted write
            # barrier exposes nothing — new lookups resolve through the
            # cold set, and stragglers holding this ref get 503 retries
            # until the barrier TTL, whose retry hydrates
            frag.end_capture(end_capture_tag)
        frag.close()  # frees the fragment's own device-cache residency
        # deletion order is load-bearing: the .snap goes LAST so a crash
        # mid-eviction leaves either a complete local fragment or
        # nothing — never a bare artifact that would reopen as an empty
        # shadow of the stored object
        for p in (frag.wal_path, frag.cache_path, frag.snap_path):
            if p is not None:
                try:
                    os.remove(p)
                except OSError:
                    pass
        DEVICE_CACHE.invalidate_owner_shard(self._stack_token, shard)
        return True

    def adopt_fragment(self, shard: int, blob: bytes,
                       on_ready=None) -> Fragment:
        """Tier hydration target: materialize a demoted fragment from
        its snapshot object (`to_bytes` output). Any retained WAL tail —
        a crash between a hydration's local snapshot and its WAL
        truncate can leave one — replays AFTER the snapshot applies (its
        records postdate the upload by construction), so it is collected
        up front; left in place, open() would replay it UNDER the
        from_bytes replacement and lose it.

        The fragment is PUBLISHED (inserted into `fragments`) only after
        its contents are complete and `on_ready` ran — callers hold no
        other reference, so `on_ready` (the tier's bootstrap-watch
        capture arming) observes a state no write can have moved yet."""
        path = self._fragment_path(shard)
        tail: list = []
        if path is not None and os.path.exists(path + ".wal"):
            tail = list(walmod.replay_wal(path + ".wal"))
            os.remove(path + ".wal")
        frag = Fragment(
            path,
            self.index,
            self.field,
            self.name,
            shard,
            mutex=self.mutex,
            max_op_n=self.max_op_n,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
        ).open()
        frag.from_bytes(blob)
        if tail:
            frag.apply_transfer_records(walmod.encode_records(tail))
        if on_ready is not None:
            on_ready(frag)
        with self._mu:
            existing = self.fragments.get(shard)
            if existing is not None:
                # lost a (single-flight-guarded, so unexpected) race:
                # the published fragment wins; ours was never visible
                frag.end_capture(None)
                frag.close()
                return existing
            frag.on_mutate = lambda s=shard: self._on_fragment_mutate(s)
            self.fragments[shard] = frag
        return frag

    # -- stacked operands for the compiled query path ----------------------
    #
    # A "stack" is one row materialized across a shard list as a dense
    # uint32[S, W] device array (shard-axis sharded under an active mesh).
    # Staging goes through the HBM residency layer (pilosa_tpu/hbm/):
    # big stacks are split into shard-major EXTENTS that page in/out of
    # the budgeted device cache individually, keyed by the fragments'
    # mutation versions — a write to any covered fragment makes the keys
    # miss and the affected slices rebuild lazily. Callers on the compiled
    # query path pass their lowering's ExtentTable so the staged extents
    # stay pinned through the plan's dispatch.

    def _frags_for(self, shards: tuple) -> list:
        """Fragment list for a shard span, hydrating any COLD member
        through the tier resolver (single-flight; a missing shard with
        no cold copy stays None and reads as zeros, as before). Also
        feeds the tier's LRU touch clock so hot working sets never look
        idle to the demote ticker."""
        with self._mu:
            frags = [self.fragments.get(s) for s in shards]
        res = self.cold_resolver
        if res is not None:
            if any(f is None for f in frags):
                cold = res.cold_shards(self)
                for i, s in enumerate(shards):
                    if frags[i] is None and s in cold:
                        frags[i] = res.resolve(self, s)
            res.touch_many(
                self, [s for s, f in zip(shards, frags) if f is not None]
            )
        return frags

    def _stack_key(self, kind: str, ident, shards: tuple) -> tuple:
        # fragment versions are NOT part of the base key: staging appends
        # each extent's OWN shard-span version slice, so a write to one
        # shard re-keys only the covering extent instead of the whole
        # stack (the dirty-extent property the invalidation relies on)
        from pilosa_tpu.parallel import mesh as pmesh

        return (self._stack_token, kind, ident, shards, pmesh.mesh_epoch())

    @staticmethod
    def _frag_versions(frags) -> tuple:
        return tuple(f.version if f is not None else -1 for f in frags)

    # -- cross-fragment merge barrier (core/merge.py) ----------------------

    def sync_pending(self, shards=None, frags=None) -> None:
        """Read barrier over many fragments at once: gather every listed
        (default: every) fragment's staged pending delta and merge the
        whole burst in ONE batched pass — device program or vectorized
        host pass by the `merge-device-threshold` crossover — instead of
        one `_sync_locked` host pass per fragment. Afterwards, resident
        stack extents covering the written shards are patched in place
        on device (or dropped when unpatchable) so sustained mixed load
        does not oscillate between invalidate and ~32 MB re-stages. No
        fragment lock is held across another's, and none during the
        merge itself."""
        from pilosa_tpu.core import merge as merge_mod

        if frags is None:
            with self._mu:
                if shards is None:
                    frags = list(self.fragments.values())
                else:
                    frags = [self.fragments.get(s) for s in shards]
        merges = merge_mod.merge_barrier(frags)
        if merges:
            # result-cache repair/re-key: the SAME merged word deltas
            # that patch resident device extents below also patch cached
            # Count scalars in place (count += popcount(delta & ~old)),
            # so a repeat Count after a set-only burst serves from host
            # memory without re-reading a single operand word
            RESULT_CACHE.note_merges(self._stack_token, merges)
        # reconcile ONLY the shards this barrier covered: a query over a
        # disjoint shard span must not invalidate (and forget) other
        # shards' still-patchable extents — they stay dirty until their
        # own barrier merges them
        synced = {f.shard for f in frags if f is not None}
        with self._mu:
            dirty = self._dirty_staged & synced
        if merges or dirty:
            self._reconcile_extents(merges, dirty)

    def _reconcile_extents(self, merges, dirty: set) -> None:
        """Patch-or-invalidate every stack entry covering a shard whose
        staged delta just merged (or merged earlier via a per-fragment
        host barrier — `dirty` remembers those). An entry is patched
        only when every affected shard's fragment was `clean` (moved
        base -> base+n_parts by exactly the captured staged batches;
        batches staged mid-barrier stay pending and re-key the entry
        forward at their own barrier) AND the entry is keyed at exactly
        the pre-burst version; anything else drops it — the version
        keys already made it unservable."""
        patches = {m.shard: m for m in merges if m.clean}
        affected = dirty | {m.shard for m in merges}
        stale = affected - set(patches)
        if not affected:
            return
        from pilosa_tpu.parallel import mesh as pmesh

        patchable = pmesh.active_mesh() is None  # never touch sharded arrays
        for key, cover, is_extent in DEVICE_CACHE.owner_entries(
            self._stack_token
        ):
            if cover is None:
                # no registered coverage => not version-keyed: drop
                # conservatively (same rule as invalidate_owner_shard)
                DEVICE_CACHE.invalidate(key)
                continue
            hit = cover & affected
            if not hit:
                continue
            if (
                not patchable
                or (hit & stale)
                or not self._patch_entry(key, hit, patches, is_extent)
            ) and not self._entry_current(key, hit):
                # keep-if-current guards the races this reconcile can't
                # see: a concurrent barrier may have ALREADY patched the
                # entry to the fragments' live versions (this thread's
                # stale apply lost the generation race), or a dirty
                # marker may describe a write another barrier fully
                # reconciled — an entry keyed at the current versions
                # is exact by construction and must not be dropped
                DEVICE_CACHE.invalidate(key)
        with self._mu:
            self._dirty_staged -= affected

    def _entry_current(self, key, hit: set) -> bool:
        """True when the entry's version key matches every hit shard's
        fragment CURRENT version — i.e. the entry is exact right now
        and any 'stale' verdict about it is outdated. Lock-free version
        reads: a racing mutation makes the entry stale-by-key anyway
        (a wrong keep leaks one unservable entry until eviction, never
        a wrong answer), and the mutation re-marks the shard dirty so a
        later reconcile retries."""
        if key[0] != self._stack_token or len(key) < 6:
            return False
        tail = key[5:]
        if tail[0] == "ext" and len(tail) == 4:
            versions = tail[3]
            lo = tail[2] * tail[1]
        elif tail[0] == "mono" and len(tail) == 2:
            versions = tail[1]
            lo = 0
        else:
            return False
        span = key[3][lo : lo + len(versions)]
        for p, s in enumerate(span):
            if s in hit:
                frag = self.fragments.get(s)
                if frag is None or versions[p] != frag.version:
                    return False
        return True

    def _patch_entry(self, key, hit: set, patches, is_extent: bool) -> bool:
        """Rebuild one resident stack entry as (old contents | merged
        delta) ON DEVICE and re-insert it under the post-merge version
        key. True = reconciled (patched, or provably gone); False = the
        caller must invalidate. Exactness: the entry must be keyed at
        each patched fragment's pre-burst `base_version`, and the
        fragment must have been `clean` — content(base) | delta ==
        content(new) holds only when nothing else mutated in between."""
        import jax

        from pilosa_tpu.parallel import mesh as pmesh

        if key[0] != self._stack_token or len(key) < 6:
            return False
        if key[4] != pmesh.mesh_epoch():
            return False  # pre-mesh-change entry: a patched key is dead
        kind, ident, shards_t = key[1], key[2], key[3]
        tail = key[5:]
        if tail[0] == "ext" and len(tail) == 4:
            rows_per, ei, versions = tail[1], tail[2], tail[3]
            lo = ei * rows_per
        elif tail[0] == "mono" and len(tail) == 2:
            versions = tail[1]
            lo = 0
        else:
            return False
        span = shards_t[lo : lo + len(versions)]
        if kind == "row":
            row_ids = [ident]
        elif kind == "planes":
            row_ids = list(ident)
        else:
            return False
        upd = list(versions)
        deltas = []
        for p, s in enumerate(span):
            if s not in hit:
                continue
            m = patches.get(s)
            if m is None or versions[p] != m.base_version:
                return False
            upd[p] = m.new_version
            deltas.append((p, m))
        if not deltas:
            return False
        arr = DEVICE_CACHE.get(key)
        if arr is None:
            return True  # evicted meanwhile: nothing resident to go stale
        # batch the patch per ENTRY: every dirty (plane, shard-position)
        # delta lands through ONE gather | OR | scatter with stacked
        # index arrays, so a burst smeared over S shards costs one
        # whole-extent copy instead of S of them — the old per-position
        # `.at[p].set` cascade paid a full-extent copy per dirty shard
        # (~11.6 s for a 50k-position burst over 954 shards,
        # BENCH_NOTES round-10's named caveat)
        idx_p: List[int] = []
        idx_d: List[int] = []
        blocks: List[np.ndarray] = []
        for p, m in deltas:
            for d, rid in enumerate(row_ids):
                if rid not in m.rows:
                    continue  # row untouched by the delta: re-key only
                widx, wvals = m.word_delta(rid)
                if not len(widx):
                    continue
                delta = np.zeros(WORDS_PER_ROW, np.uint32)
                delta[widx] = wvals
                blocks.append(delta)
                idx_p.append(p)
                idx_d.append(d)
        new_arr = arr
        n_batches = 0
        # bounded scatter batches: stacking EVERY delta block at once
        # would spike host+device memory by (dirty positions x touched
        # rows x row bytes) — a whole-index smear into a monolithic
        # deep-field entry could transiently allocate gigabytes. 256
        # blocks (~32 MB at the default shard width) keeps the spike
        # bounded while the cascade stays O(entries + deltas/256)
        # device ops, never O(dirty shards).
        CHUNK = 256
        for c0 in range(0, len(blocks), CHUNK):
            ddev = jax.device_put(np.stack(blocks[c0:c0 + CHUNK]))
            if kind == "row":
                pi = np.asarray(idx_p[c0:c0 + CHUNK])
                new_arr = new_arr.at[pi].set(new_arr[pi] | ddev)
            else:
                di = np.asarray(idx_d[c0:c0 + CHUNK])
                pi = np.asarray(idx_p[c0:c0 + CHUNK])
                new_arr = new_arr.at[di, pi].set(new_arr[di, pi] | ddev)
            n_batches += 1
        new_key = key[:5] + (
            ("ext", rows_per, ei, tuple(upd))
            if tail[0] == "ext"
            else ("mono", tuple(upd))
        )
        DEVICE_CACHE.put(
            new_key, new_arr, extent=is_extent, shards=span, index=self.index
        )
        DEVICE_CACHE.invalidate(key)
        from pilosa_tpu.hbm import residency as hbm_res

        hbm_res.note_extent_patch(batches=n_batches)
        return True

    def row_stack(self, row_id: int, shards, extents=None,
                  parts: bool = False) -> Optional[object]:
        """uint32[S, W] device stack of one row over `shards`, or None when
        no listed shard has a fragment (the row is wholly absent).
        `extents` (hbm.ExtentTable) receives the pinned extent keys;
        `parts` returns the per-extent arrays unassembled (the
        plane-streamed aggregate path reduces across them in program
        instead of paying a device-side concat per staging)."""
        from pilosa_tpu.hbm import residency as hbm_res

        shards = tuple(shards)
        frags = self._frags_for(shards)
        if all(f is None for f in frags):
            return None
        # merge the staged burst (all touched fragments, one pass) and
        # patch/drop covering extents BEFORE versions are read below, so
        # the staged keys reflect the merged state
        self.sync_pending(frags=frags)
        key = self._stack_key("row", row_id, shards)

        def build_slice(lo: int, hi: int):
            rows = [
                f.row_words(row_id)
                if f is not None
                else np.zeros(WORDS_PER_ROW, np.uint32)
                for f in frags[lo:hi]
            ]
            return np.stack(rows)

        return hbm_res.stage_row_stack(
            key, len(shards), build_slice, table=extents,
            versions=self._frag_versions(frags), shards=shards,
            index=self.index, parts=parts,
        )

    def stage_bulk(self, shards: np.ndarray, positions: np.ndarray) -> None:
        """Bulk-ingest router (the write-side hot path): ONE argsort over
        the whole batch splits the fragment positions into per-shard
        views; per-fragment cost is then a WAL frame + a pending-buffer
        append (Fragment.stage_positions with notify=False). The
        device-cache work every write owes — dropping the touched
        fragments' row entries and the dirty shards' covering extents —
        runs as two batched passes at the end instead of two global-lock
        hits per shard."""
        if not len(shards):
            return
        # hand-rolled grouping instead of utils/arrays.group_slices: this
        # is THE write hot path, and group_slices' stable argsort costs
        # ~4x quicksort on uint64 keys while its per-group index arrays
        # force a fancy-gather per shard — np.split on the pre-permuted
        # positions hands out views. Stability is not needed: set bits
        # commute.
        order = np.argsort(shards)
        sh = shards[order]
        pos = positions[order]
        bounds = np.flatnonzero(sh[1:] != sh[:-1]) + 1
        starts = np.concatenate(([0], bounds)).astype(np.int64)
        uniq = sh[starts]
        chunks = np.split(pos, bounds)
        tokens = []
        dirty = []
        # one group-commit fsync round for the WHOLE batch at barrier
        # exit: each stage_positions defers its durability wait, so a
        # 100-shard import pays one commit round, not 100 — and
        # concurrent import calls coalesce into each other's rounds
        with walmod.GROUP_COMMIT.barrier():
            for shard, chunk in zip(uniq.tolist(), chunks):
                frag = self.fragment(int(shard))
                frag.stage_positions(chunk, notify=False)
                tokens.append(frag._token)
                tokens.append(frag._stack_token)
                dirty.append(int(shard))
        DEVICE_CACHE.invalidate_owners(tokens)
        # view-level stack entries: ad-hoc (uncovered) builds like the
        # TopN tally bundles are not version-keyed, so they drop NOW;
        # coverage-registered extents ARE version-keyed (never served
        # stale) and defer to the merge barrier, which patches resident
        # ones in place with the merged delta instead of forcing a
        # ~extent-sized PCIe re-stage per touched extent
        with self._clock_mu:
            self.mutation_clock += 1
        DEVICE_CACHE.invalidate_owner_uncovered(self._stack_token)
        # result-cache dirty reporting, batched like the device pass:
        # stale non-repairable results drop now, repairable Counts wait
        # for the barrier's repair (stage_positions ran notify=False, so
        # the per-fragment on_mutate funnel did not fire)
        RESULT_CACHE.note_mutations(self._stack_token, dirty)
        coherence_hub.note_view_mutation(self, dirty)
        with self._mu:
            self._dirty_staged.update(dirty)

    def plane_stack(self, row_ids, shards, extents=None,
                    parts: bool = False) -> Optional[object]:
        """uint32[D, S, W] device stack (BSI planes × shards), or None when
        no listed shard has a fragment. Extents slice the shard axis: one
        slice pages all D planes for its shard range together. `parts`
        returns the per-extent arrays unassembled."""
        from pilosa_tpu.hbm import residency as hbm_res

        row_ids = tuple(row_ids)
        shards = tuple(shards)
        frags = self._frags_for(shards)
        if all(f is None for f in frags):
            return None
        self.sync_pending(frags=frags)
        key = self._stack_key("planes", row_ids, shards)

        def build_slice(lo: int, hi: int):
            part = frags[lo:hi]
            if not row_ids:  # bit_depth 0: empty plane axis
                return np.zeros((0, len(part), WORDS_PER_ROW), np.uint32)
            zeros = np.zeros(WORDS_PER_ROW, np.uint32)
            return np.stack(
                [
                    np.stack(
                        [
                            f.row_words(r) if f is not None else zeros
                            for f in part
                        ]
                    )
                    for r in row_ids
                ]
            )

        return hbm_res.stage_plane_stack(
            key, len(shards), build_slice, table=extents,
            versions=self._frag_versions(frags), shards=shards,
            index=self.index, parts=parts,
        )

    # -- fan-down helpers (view.go:367-474) --------------------------------

    def set_bit(self, row_id: int, col: int) -> bool:
        return self.fragment(col // SHARD_WIDTH).set_bit(row_id, col)

    def clear_bit(self, row_id: int, col: int) -> bool:
        frag = self.fragment_if_exists(col // SHARD_WIDTH)
        return frag.clear_bit(row_id, col) if frag is not None else False

    def set_value(self, col: int, bit_depth: int, value: int, clear: bool = False) -> bool:
        return self.fragment(col // SHARD_WIDTH).set_value(col, bit_depth, value, clear)

    def value(self, col: int, bit_depth: int):
        frag = self.fragment_if_exists(col // SHARD_WIDTH)
        if frag is None:
            return 0, False
        return frag.value(col, bit_depth)

    def row_positions(self, row_id: int) -> np.ndarray:
        """Absolute columns of a row across all shards (host; for exports)."""
        cols = []
        for shard in self.available_shards():
            frag = self.fragment_if_exists(shard)  # hydrates cold shards
            if frag is None:
                continue
            p = frag.row_positions(row_id)
            if len(p):
                cols.append(p.astype(np.uint64) + np.uint64(shard) * np.uint64(SHARD_WIDTH))
        return np.concatenate(cols) if cols else np.empty(0, np.uint64)
