"""Key translation: string key <-> uint64 id stores.

Reference: /root/reference/translate.go (TranslateStore iface :35,
InMemTranslateStore :195) and boltdb/translate.go:48-310 (file-backed store
with monotonic ids, single-writer append log consumed by replicas over HTTP,
http/translator.go:44-128).

TPU-native design: translation is inherently a serial string-keyed KV and
must stay OFF the device query path (SURVEY.md hard-part #4). This store is
host-only: an in-memory bidirectional map backed by an append-only log file
(length-prefixed records), replayed on open. Monotonic ids start at 1 (id 0
is reserved as "not found", matching boltdb/translate.go semantics).
Replication: `entries_since(offset)` exposes the append log so a replica (or
the HTTP translator endpoint) can follow the primary, mirroring
TranslateEntryReader (holder.go:738-880).
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from pilosa_tpu.utils.locks import TrackedRLock

_REC = struct.Struct("<QI")  # id, key-length ; followed by key bytes


class TranslateError(Exception):
    pass


class ReadOnlyError(TranslateError):
    """Raised when writing to a non-primary (replica) store.

    Reference: boltdb/translate.go returns ErrTranslateStoreReadOnly for
    non-coordinator writes; callers forward the write to the primary."""


class TranslateStore:
    """Bidirectional string<->id map with an append-only on-disk log.

    One store per keyed index (columns) and one per keyed field (rows),
    mirroring the reference's per-index/per-field boltdb stores."""

    def __init__(self, path: Optional[str] = None, read_only: bool = False):
        self.path = path
        self.read_only = read_only
        # single-writer replication hooks (reference: boltdb/translate.go
        # forwards non-primary writes; holder.go:785-880 replica follower).
        # forward_fn(keys) -> ids: ask the primary to allocate.
        # catchup_fn() -> None: pull + apply the primary's new entries.
        self.forward_fn = None
        self.catchup_fn = None
        self._lock = TrackedRLock("translate.lock")
        self._by_key: Dict[str, int] = {}
        self._by_id: Dict[int, str] = {}
        self._next_id = 1
        self._log_size = 0  # byte offset == replication offset
        self._fh = None

    # -- lifecycle ---------------------------------------------------------

    def open(self) -> "TranslateStore":
        if self.path:
            if os.path.exists(self.path):
                self._replay()
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fh = open(self.path, "ab")
        return self

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def _replay(self) -> None:  # lock-free: open()-time replay, pre-publication
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        n = len(data)
        while off + _REC.size <= n:
            id_, klen = _REC.unpack_from(data, off)
            end = off + _REC.size + klen
            if end > n:  # truncated tail record (crash mid-append): drop it
                break
            key = data[off + _REC.size : end].decode("utf-8")
            self._by_key[key] = id_
            self._by_id[id_] = key
            self._next_id = max(self._next_id, id_ + 1)
            off = end
        self._log_size = off
        if off < n:  # truncate the torn tail so appends realign
            with open(self.path, "r+b") as f:
                f.truncate(off)

    # -- writes ------------------------------------------------------------

    def translate_key(self, key: str) -> int:
        """Return the id for key, creating it if absent (single-writer)."""
        return self.translate_keys([key])[0]

    def translate_keys(self, keys: Sequence[str]) -> List[int]:
        if self.read_only:
            # Forward unknown keys to the primary OUTSIDE the lock (a slow
            # coordinator must not freeze local reads), then apply.
            with self._lock:
                missing = sorted({k for k in keys if k not in self._by_key})
            if missing:
                if self.forward_fn is None:
                    raise ReadOnlyError(
                        f"translate store is read-only; forward {missing[0]!r} to primary"
                    )
                ids = self.forward_fn(missing)
                if len(ids) != len(missing):
                    raise TranslateError(
                        f"primary returned {len(ids)} ids for {len(missing)} keys"
                    )
                self.apply_entries(zip(ids, missing))
            with self._lock:
                try:
                    return [self._by_key[k] for k in keys]
                except KeyError as e:
                    raise TranslateError(
                        f"key {e.args[0]!r} missing after primary forward"
                    ) from None
        with self._lock:
            out = []
            new: List[Tuple[int, str]] = []
            for key in keys:
                id_ = self._by_key.get(key)
                if id_ is None:
                    id_ = self._next_id
                    self._next_id += 1
                    self._by_key[key] = id_
                    self._by_id[id_] = key
                    new.append((id_, key))
                out.append(id_)
            if new:
                self._append(new)
            return out

    def apply_entries(self, entries: Iterable[Tuple[int, str]]) -> None:
        """Apply replicated entries from the primary (replica follow path).

        A conflicting mapping (same id, different key) means the replica
        allocated locally instead of forwarding writes to the primary —
        unrecoverable divergence, so fail loudly rather than skip."""
        with self._lock:
            new = []
            for id_, key in entries:
                existing = self._by_id.get(id_)
                if existing is not None:
                    if existing != key:
                        raise TranslateError(
                            f"replication conflict: id {id_} is {existing!r} "
                            f"locally but {key!r} on primary"
                        )
                    continue
                self._by_id[id_] = key
                self._by_key[key] = id_
                self._next_id = max(self._next_id, id_ + 1)
                new.append((id_, key))
            if new:
                self._append(new)

    def _append(self, recs: List[Tuple[int, str]]) -> None:
        blob = b"".join(
            _REC.pack(id_, len(kb)) + kb
            for id_, kb in ((i, k.encode("utf-8")) for i, k in recs)
        )
        if self._fh:
            # file mode: offsets are byte positions in the log
            self._log_size += len(blob)
            self._fh.write(blob)
            self._fh.flush()
        else:
            # memory mode: offsets are entry indexes (entries_since serves
            # from the map) — keep the two currencies from mixing
            self._log_size += len(recs)

    # -- reads -------------------------------------------------------------

    def find_key(self, key: str) -> Optional[int]:
        """id for key, or None — never creates (read path)."""
        return self._by_key.get(key)

    def key_for_id(self, id_: int) -> Optional[str]:
        key = self._by_id.get(id_)
        if key is None and self.catchup_fn is not None:
            # stale replica: pull the primary's new entries once and retry
            try:
                self.catchup_fn()
            except Exception:
                return None
            key = self._by_id.get(id_)
        return key

    def keys_for_ids(self, ids: Sequence[int]) -> List[Optional[str]]:
        # catch up from the primary at most ONCE per batch, then serve the
        # whole batch from the local map
        if self.catchup_fn is not None and any(i not in self._by_id for i in ids):
            try:
                self.catchup_fn()
            except Exception:
                pass
        return [self._by_id.get(i) for i in ids]

    def max_id(self) -> int:
        return self._next_id - 1

    def __len__(self) -> int:
        return len(self._by_key)

    # -- replication -------------------------------------------------------

    @property
    def write_offset(self) -> int:
        """Current append-log byte offset (replication high-water mark)."""
        return self._log_size

    def entries_since(self, offset: int = 0) -> Tuple[List[Tuple[int, str]], int]:
        """Entries appended at/after byte offset; returns (entries, new_offset).

        Reference: the HTTP translate-data endpoint streams the boltdb log
        from an offset (http/translator.go:44-128)."""
        with self._lock:
            if not self.path or not os.path.exists(self.path):
                # memory-only store: serve from the map (offset = entry index)
                items = sorted(self._by_id.items())
                return items[offset:], len(items)
            if self._fh:
                self._fh.flush()
            with open(self.path, "rb") as f:
                f.seek(offset)
                data = f.read()
        out = []
        off = 0
        while off + _REC.size <= len(data):
            id_, klen = _REC.unpack_from(data, off)
            end = off + _REC.size + klen
            if end > len(data):
                break
            out.append((id_, data[off + _REC.size : end].decode("utf-8")))
            off = end
        return out, offset + off
