"""Cross-fragment deferred-delta merge barrier.

`Fragment._sync_locked` merges each fragment's staged ingest delta
independently at its own read barrier — correct, but a 954-fragment
ingest burst then pays 954 per-fragment host passes (each a handful of
small-numpy calls plus a lock, with per-row rewrite work on top) the
first time a query reads the view. This module is the view/field-level
collector: it gathers the pending position buffers of every staged
fragment a read is about to touch, packs them into ONE uint64 key
array (segment id in the high bits, position in the low bits),
sort/dedups the whole burst in one pass — on device (ops/merge.py, one
program launch) at or above the `merge-device-threshold` crossover, as
one vectorized host pass below it — and hands each fragment its merged
slice back as a parked DELTA LAYER (pending-part format). The barrier
is O(burst): the row-store materialization rides each fragment's next
HOST read (`_sync_locked` folds layers into the vectorized merge it
already runs), while the device stays exact immediately — resident
extents are patched in place with the same merged word deltas
(core/view.py), so warm device-served queries never wait on a host
row rewrite at all.

Concurrency handshake (no fragment lock is ever held across another's,
and none is held during the merge itself):

- snapshot phase: under each fragment's lock, the barrier records a
  REFERENCE to the current pending parts list, its length, the
  fragment's `_pending_gen` and `_staged_base_version`. Nothing is
  popped — a concurrent reader hitting `_sync_locked` mid-merge still
  sees (and merges) everything, staying exact.
- apply phase: under each fragment's lock again,
  `Fragment.apply_merged_delta` re-checks the generation. If a
  concurrent `_sync_locked` already merged the captured parts the
  apply is skipped (the work was done exactly once by the other
  path); otherwise the merged delta layer parks, the captured parts
  are trimmed, and the generation bumps.

The per-fragment outcome (`FragMerge`) carries what the view needs for
in-place extent patching (hbm/residency.py): which rows changed, their
word-level deltas, and the version window [base, base + n_parts] the
patch is valid for — a patch is only taken when the fragment saw no
other mutation in between (`clean`), since anything else either merged
the delta itself or invalidated the covering extents already.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.ops import merge as ops_merge
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXPONENT

# Crossover between the batched host merge and the device program:
# bursts with at least this many total pending positions dispatch the
# sort/dedup kernel; smaller deltas stay on the vectorized host path
# (a 200-position delta must not pay a program dispatch). < 0 disables
# the device path outright; 0 forces it (tests use both extremes).
# None = AUTO: 65536 on a real accelerator, device-off on the CPU
# backend — there the "device" is the same silicon reached through
# XLA's ~5x-slower sort comparator (ops/merge.py), so the dispatch can
# never pay for itself at any burst size (np.unique measured ~6x
# faster than the XLA CPU sort across 2^18..2^22 keys).
_ACCEL_DEVICE_THRESHOLD = 65536


def _env_threshold() -> Optional[int]:
    raw = os.environ.get("PILOSA_TPU_MERGE_DEVICE_THRESHOLD")
    try:
        return int(raw) if raw not in (None, "") else None
    except ValueError:
        return None


_device_threshold: Optional[int] = _env_threshold()
_auto_threshold: List[int] = []  # backend probe cache (lazy: jax init)

_stats_mu = TrackedLock("merge.stats_mu")
_counters: Dict[str, float] = {
    "barrier_ms": 0.0,  # cumulative wall ms spent in merge barriers
    "barriers": 0,  # barrier invocations that merged at least one fragment
    "batches": 0,  # staged pending buffers merged (barrier + per-fragment)
    "device": 0,  # barriers that dispatched the device merge program
    "positions": 0,  # raw staged positions merged through barriers
}


_UNSET = object()


def configure(device_threshold=_UNSET) -> None:
    """Install the server's [ingest] knobs (cli/config.py ->
    server/node.py). None selects the backend-adaptive AUTO crossover.
    Process-global, like the [hbm] knobs: all in-process nodes share
    one device."""
    global _device_threshold
    if device_threshold is not _UNSET:
        _device_threshold = (
            None if device_threshold is None else int(device_threshold)
        )


def device_threshold() -> int:
    """The resolved crossover (AUTO probes the backend once, lazily —
    importing this module must not initialize jax)."""
    if _device_threshold is not None:
        return _device_threshold
    if not _auto_threshold:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - probe failure -> host path
            backend = "cpu"
        _auto_threshold.append(
            -1 if backend == "cpu" else _ACCEL_DEVICE_THRESHOLD
        )
    return _auto_threshold[0]


def reset_stats() -> None:
    with _stats_mu:
        for k in _counters:
            _counters[k] = 0 if k != "barrier_ms" else 0.0


def note_host_sync(n_batches: int) -> None:
    """Book a per-fragment `_sync_locked` merge (the non-barrier path)
    so `ingest.merge_batches` counts every staged buffer exactly once
    however it got merged."""
    with _stats_mu:
        _counters["batches"] += n_batches


def stats_snapshot() -> Dict[str, float]:
    """ingest.merge_* gauge values (NodeServer.publish_cache_gauges)."""
    with _stats_mu:
        return dict(_counters)


class FragMerge:
    """One fragment's barrier outcome, consumed by the view's extent
    reconciliation. `rows` is the fragment's touched row ids (ascending
    python ints); `starts`/`ends` index into the barrier's GLOBAL merged
    column/cumsum arrays (`cols`/`cum`, shared across all FragMerges of
    one barrier — slicing is lazy, only for rows a patch actually
    needs). Each row's slice is its sorted-unique staged DELTA, so the
    word-OR handed to the extent patcher is exactly the bits the burst
    set. `clean` means the fragment moved from `base_version` to
    `new_version` by EXACTLY the captured staged batches (structurally
    true whenever the apply landed: pending parts are a contiguous
    version range, since any non-stage mutation drains pending first
    under the fragment lock), so a resident extent keyed at
    `base_version` can be patched in place to `new_version` instead of
    re-staged — even mid-burst, with later batches still pending and
    re-keying the extent forward at their own barrier."""

    __slots__ = (
        "frag",
        "shard",
        "applied",
        "clean",
        "base_version",
        "new_version",
        "rows",
        "cols",
        "cum",
        "starts",
        "ends",
        "old_words",
    )

    def __init__(self, frag, rows, cols, cum, starts, ends):
        self.frag = frag
        self.shard = frag.shard
        self.applied = False
        self.clean = False
        self.base_version = -1
        self.new_version = -1
        self.rows = rows  # python list of touched row ids, ascending
        self.cols = cols
        self.cum = cum
        self.starts = starts
        self.ends = ends
        # row id -> host words at base_version, captured BEFORE the
        # delta layer parked — only for rows the result cache registered
        # interest in (core/resultcache.py count repair)
        self.old_words: Dict[int, np.ndarray] = {}

    def word_delta(self, row_id: int):
        """(word_idx, word_val) arrays of this row's merged delta, for
        the device-side extent patch."""
        i = self.rows.index(row_id)
        s, e = self.starts[i], self.ends[i]
        return ops_merge.word_or_from_sorted(self.cols[s:e], self.cum[s:e])


def _repair_interest(frag) -> set:
    """Rows of this fragment's (index, field, view) that repairable
    cached Counts are watching (core/resultcache.py). Lazy import: the
    cache module is light, but core/merge must stay importable without
    it mid-bootstrap; the common path is one dict lookup returning
    empty."""
    from pilosa_tpu.core.resultcache import RESULT_CACHE

    return RESULT_CACHE.interest_rows(frag.index, frag.field, frag.view)


def merge_barrier(frags) -> List[FragMerge]:
    """Merge the pending deltas of every staged fragment in `frags` as
    one batched pass. Returns a FragMerge per fragment that had a
    delta captured (applied or not). Mutex fragments never stage, so
    they are skipped by construction.

    The barrier's cost is O(burst), independent of fragment count and
    of accumulated fragment content: pack, sort/dedup (device program
    or np.unique) and per-row boundary math all run GLOBALLY over the
    staged positions, and each fragment's apply just trims its pending
    batches and parks its merged slice as a delta layer (the row-store
    materialization rides the fragment's next HOST read barrier — the
    device is kept exact directly, via in-place extent patches built
    from the FragMerge word deltas). The per-fragment host path pays
    ~a dozen small-numpy calls per fragment plus per-row rewrite work;
    at bench geometry (954 fragments x ~30 rows) that overhead IS the
    merge cost."""
    staged = [f for f in frags if f is not None and f._pending_n]
    if not staged:
        return []
    t0 = time.perf_counter()
    caps = []
    for f in staged:
        snap = f.pending_snapshot()
        if snap is not None:
            caps.append((f,) + snap)
    if not caps:
        return []

    # pack (segment, position) into one uint64 keyspace: ROW_SPAN is
    # the per-fragment span, rounded up to a SHARD_WIDTH multiple so
    # key >> SHARD_WIDTH_EXPONENT stays (segment, row)-unique and the
    # low 5 bits stay the in-word bit (the kernel's word-OR relies on
    # both). Pathological row ids that would overflow the packing
    # (2^63 guard) fall back to per-fragment host merges.
    parts_flat: List[np.ndarray] = []
    part_seg: List[int] = []
    for i, cap in enumerate(caps):
        for part in cap[1]:
            parts_flat.append(part)
            part_seg.append(i)
    combined = (
        parts_flat[0] if len(parts_flat) == 1 else np.concatenate(parts_flat)
    )
    max_pos = int(combined.max())
    row_span = ((max_pos >> SHARD_WIDTH_EXPONENT) + 1) << SHARD_WIDTH_EXPONENT
    if len(caps) * row_span >= 1 << 63:
        for cap in caps:
            cap[0].sync_pending_now()
        return []
    if len(caps) > 1 or part_seg[0]:
        seg_off = np.repeat(
            np.array(part_seg, np.uint64) * np.uint64(row_span),
            [len(p) for p in parts_flat],
        )
        combined = combined + seg_off
    rows_per_seg = row_span >> SHARD_WIDTH_EXPONENT

    thr = device_threshold()
    use_device = thr >= 0 and len(combined) >= thr
    if use_device:
        merged, cum = ops_merge.merge_keys_device(combined)
    else:
        merged, cum = ops_merge.merge_keys_host(combined)

    # per-row boundaries over the whole burst, then plain-list slices
    # per fragment (the apply must not touch numpy per row); `local`
    # de-offsets the keyspace once so each fragment can park its slice
    # as a delta layer in pending-part format
    seg_edges = np.searchsorted(
        merged, np.arange(len(caps) + 1, dtype=np.uint64) * np.uint64(row_span)
    )
    local = merged - np.repeat(
        np.arange(len(caps), dtype=np.uint64) * np.uint64(row_span),
        np.diff(seg_edges),
    )
    cols_g = (merged & np.uint64(SHARD_WIDTH - 1)).astype(np.uint32)
    rowkeys = merged >> np.uint64(SHARD_WIDTH_EXPONENT)
    bounds = np.flatnonzero(rowkeys[1:] != rowkeys[:-1]) + 1
    starts_g = np.empty(len(bounds) + 1, np.int64)
    starts_g[0] = 0
    starts_g[1:] = bounds
    ends_g = np.empty_like(starts_g)
    ends_g[:-1] = bounds
    ends_g[-1] = len(merged)
    rk_start = rowkeys[starts_g]
    row_of = (rk_start % np.uint64(rows_per_seg)).astype(np.int64).tolist()
    starts_l = starts_g.tolist()
    ends_l = ends_g.tolist()
    frag_edges = np.searchsorted(
        rk_start,
        np.arange(len(caps) + 1, dtype=np.uint64) * np.uint64(rows_per_seg),
    ).tolist()

    seg_edges_l = seg_edges.tolist()
    out: List[FragMerge] = []
    n_batches = 0
    for i, (f, parts, n_parts, gen, base_version) in enumerate(caps):
        rlo, rhi = frag_edges[i], frag_edges[i + 1]
        if rlo == rhi:
            continue
        rows_i = row_of[rlo:rhi]
        fm = FragMerge(
            f, rows_i, cols_g, cum, starts_l[rlo:rhi], ends_l[rlo:rhi]
        )
        fm.base_version = base_version
        # count-repair old-words capture: for rows a cached Count is
        # watching, read the row's host words at base_version NOW —
        # after the apply below the fragment's content has moved past
        # the base and popcount(delta & ~old) is no longer computable.
        # EVERY interest row is captured, not just the burst's: a
        # repair-spec tree patch (core/resultcache.py) needs the
        # UNTOUCHED leaves' words from the same consistent base
        # snapshot to evaluate op(old)/op(new) — an untouched row's
        # capture equals its merged content, so it serves both sides.
        # A concurrent _sync_locked between this read and the apply
        # bumps the generation, the apply returns None, and the capture
        # is discarded with the failed FragMerge — never applied stale.
        want = _repair_interest(f)
        for rid in want:
            fm.old_words[rid] = f.premerge_row_words(rid)
        # the layer is COPIED out of the shared burst buffer: a view
        # would pin the whole round's merged array until the last
        # fragment's host read materializes it
        res = f.apply_merged_delta(
            local[seg_edges_l[i] : seg_edges_l[i + 1]].copy(),
            n_parts, sum(map(len, parts)), gen,
        )
        if res is not None:
            fm.applied = True
            # the captured delta moves content EXACTLY base ->
            # base+n_parts: pending parts are always a contiguous
            # version range (any non-stage mutation drains pending
            # first, under the fragment lock), so batches staged AFTER
            # the snapshot stay pending and re-key the extent forward
            # at THEIR barrier — the patch chain never breaks under
            # continuous ingest
            fm.new_version = base_version + n_parts
            fm.clean = True
            n_batches += n_parts
        out.append(fm)

    dt_ms = (time.perf_counter() - t0) * 1000.0
    with _stats_mu:
        _counters["barrier_ms"] += dt_ms
        _counters["barriers"] += 1
        _counters["batches"] += n_batches
        _counters["positions"] += len(combined)
        if use_device:
            _counters["device"] += 1
    return out
