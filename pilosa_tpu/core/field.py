"""Field: typed container of views.

Reference: /root/reference/field.go — types set / int(BSI) / time / mutex /
bool (field.go:56-62); options persisted as metadata (field.go:522-587);
BSI group with Min/Max/Base/BitDepth (field.go:1562); time-quantum view
expansion on SetBit (field.go:927, time.go:91)."""

from __future__ import annotations

import json
import os
import re
from dataclasses import asdict, dataclass, field as dc_field
from datetime import datetime
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedRLock
from pilosa_tpu.core import timeq
from pilosa_tpu.core import wal as walmod
from pilosa_tpu.core.cache import (  # single source of truth: core/cache.py
    CACHE_TYPE_LRU,
    CACHE_TYPE_NONE,
    CACHE_TYPE_RANKED,
    DEFAULT_CACHE_SIZE,
)
from pilosa_tpu.core.view import VIEW_BSI_PREFIX, VIEW_STANDARD, View
from pilosa_tpu.utils.arrays import group_slices
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXPONENT

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

FIELD_TYPES = (
    FIELD_TYPE_SET,
    FIELD_TYPE_INT,
    FIELD_TYPE_TIME,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_BOOL,
)
CACHE_TYPES = (CACHE_TYPE_RANKED, CACHE_TYPE_LRU, CACHE_TYPE_NONE)

FALSE_ROW_ID = 0  # reference: falseRowID/trueRowID, fragment.go:86-87
TRUE_ROW_ID = 1

_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")


def validate_name(name: str) -> None:
    """Reference name rules (pilosa.go validateName)."""
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid name {name!r}")


def bit_depth_of(uvalue: int) -> int:
    """Bits needed for a magnitude (>=1) (reference: bitDepth, fragment.go)."""
    return max(1, int(uvalue).bit_length())


def bsi_base(min_v: int, max_v: int) -> int:
    """Default base (reference: field.go:1552 bsiBase)."""
    if min_v > 0:
        return min_v
    if max_v < 0:
        return max_v
    return 0


@dataclass
class FieldOptions:
    type: str = FIELD_TYPE_SET
    cache_type: str = CACHE_TYPE_RANKED
    cache_size: int = DEFAULT_CACHE_SIZE
    min: int = 0
    max: int = 0
    base: int = 0
    bit_depth: int = 0
    time_quantum: str = ""
    keys: bool = False
    no_standard_view: bool = False


class Field:
    def __init__(self, path: Optional[str], index: str, name: str, options: FieldOptions):
        # Leading-underscore names are reserved for internal fields
        # (`_exists`), created only by the index itself; user-facing creation
        # paths validate separately (reference: CreateField validation).
        if not name.startswith("_"):
            validate_name(name)
        self.path = path
        self.index = index
        self.name = name
        self.options = options
        self._mu = TrackedRLock("field.mu")
        self.views: Dict[str, View] = {}
        # shards this node knows exist cluster-wide (field.go:88
        # remoteAvailableShards); local shards are derived from fragments.
        self.remote_available_shards: Set[int] = set()
        # per-row attributes (reference: field.go rowAttrStore)
        from pilosa_tpu.core.attrs import AttrStore

        self.row_attr_store = AttrStore(
            None if path is None else os.path.join(path, ".row_attrs.json")
        )
        # row key translation (reference: field.go per-field translateStore)
        from pilosa_tpu.core.translate import TranslateStore

        self.translate_store = TranslateStore(
            None if path is None else os.path.join(path, ".keys.translate")
        )

        if options.type not in FIELD_TYPES:
            raise ValueError(f"invalid field type {options.type!r}")
        if options.cache_type not in CACHE_TYPES:
            raise ValueError(f"invalid cache type {options.cache_type!r}")
        if options.type == FIELD_TYPE_INT:
            if options.min == 0 and options.max == 0:
                options.max = 2**31 - 1  # mirror of reference default range
            options.base = bsi_base(options.min, options.max)
            required = max(
                bit_depth_of(abs(options.min - options.base)),
                bit_depth_of(abs(options.max - options.base)),
            )
            if options.bit_depth == 0:
                options.bit_depth = required
            # Device BSI ladders and fused min/max are uint32: magnitudes
            # above 32 bits would silently truncate (r2 advisor). The
            # reference supports 63-bit BSI (fragment.go:90); here ranges
            # wider than 32-bit magnitudes around the base are rejected at
            # creation — values are range-checked on every write, so the
            # auto-widen paths can never exceed this afterwards.
            if max(required, options.bit_depth) > 32:
                raise ValueError(
                    f"int field range [{options.min}, {options.max}] needs "
                    f"{max(required, options.bit_depth)}-bit magnitudes; device "
                    "BSI supports at most 32 (narrow the range or shift it "
                    "closer to the base)"
                )
        if options.type == FIELD_TYPE_TIME:
            timeq.validate_quantum(options.time_quantum)

    # ------------------------------------------------------------------
    # lifecycle / persistence
    # ------------------------------------------------------------------

    @property
    def meta_path(self) -> Optional[str]:
        return None if self.path is None else os.path.join(self.path, ".meta.json")

    def open(self) -> "Field":
        if self.path is not None:
            os.makedirs(self.path, exist_ok=True)
            if os.path.exists(self.meta_path):
                self.load_meta()
            else:
                self.save_meta()
            if os.path.exists(self._avail_path):
                with open(self._avail_path) as f:
                    self.remote_available_shards.update(json.load(f))
            views_dir = os.path.join(self.path, "views")
            if os.path.isdir(views_dir):
                for vname in sorted(os.listdir(views_dir)):
                    self._view_create(vname)
        if self.options.keys:
            self.translate_store.open()
        return self

    def close(self) -> None:
        with self._mu:
            for v in self.views.values():
                v.close()
            self.translate_store.close()
            self.row_attr_store.close()

    def save_meta(self) -> None:
        if self.path is None:
            return
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(asdict(self.options), f)
        os.replace(tmp, self.meta_path)

    def load_meta(self) -> None:
        with open(self.meta_path) as f:
            data = json.load(f)
        self.options = FieldOptions(**data)

    @property
    def _avail_path(self) -> Optional[str]:
        return (
            None
            if self.path is None
            else os.path.join(self.path, ".available.shards.json")
        )

    def add_remote_available(self, shards) -> None:
        """Merge cluster-announced shards into the availability set and
        persist it, so a restarted node still knows which shards exist
        cluster-wide even if it holds no local fragment for them
        (reference: .available.shards protobuf sidecar, field.go:290-345)."""
        with self._mu:
            new = {int(s) for s in shards} - self.remote_available_shards
            if not new:
                return
            self.remote_available_shards.update(new)
            self._persist_available()

    def remove_remote_available(self, shard: int) -> None:
        """Forget one cluster-announced shard (reference:
        handleDeleteRemoteAvailableShard operational repair)."""
        with self._mu:
            if shard not in self.remote_available_shards:
                return
            self.remote_available_shards.discard(int(shard))
            self._persist_available()

    def _persist_available(self) -> None:
        """Write the availability sidecar atomically; call under _mu."""
        p = self._avail_path
        if p is not None:
            tmp = p + ".tmp"
            with open(tmp, "w") as f:
                json.dump(sorted(self.remote_available_shards), f)
            os.replace(tmp, p)

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------

    def _view_path(self, name: str) -> Optional[str]:
        if self.path is None:
            return None
        return os.path.join(self.path, "views", name)

    def _view_create(self, name: str) -> View:
        with self._mu:
            v = self.views.get(name)
            if v is None:
                is_mutex = self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL)
                # BSI views hold bit planes, not rankable rows: no cache
                # (the reference only caches standard/time views)
                is_bsi = name.startswith(VIEW_BSI_PREFIX)
                v = View(
                    name,
                    self.index,
                    self.name,
                    self._view_path(name),
                    mutex=is_mutex,
                    cache_type=CACHE_TYPE_NONE if is_bsi else self.options.cache_type,
                    cache_size=self.options.cache_size,
                ).open()
                self.views[name] = v
            return v

    def view(self, name: str = VIEW_STANDARD) -> Optional[View]:
        return self.views.get(name)

    def bsi_view_name(self) -> str:
        return VIEW_BSI_PREFIX + self.name

    def available_shards(self) -> Set[int]:
        """Union of local fragment shards + remote-known shards
        (field.go:263 AvailableShards)."""
        with self._mu:
            shards: Set[int] = set(self.remote_available_shards)
            for v in self.views.values():
                shards.update(v.available_shards())
            return shards

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def set_bit(self, row_id: int, col: int, ts: Optional[datetime] = None) -> bool:
        """Set a bit in the standard view (+ time-quantum views when
        timestamped; field.go:927 SetBit)."""
        changed = False
        if not self.options.no_standard_view:
            changed |= self._view_create(VIEW_STANDARD).set_bit(row_id, col)
        if ts is not None:
            if self.options.type != FIELD_TYPE_TIME:
                raise ValueError(f"field {self.name} is not a time field")
            for vname in timeq.views_by_time(
                VIEW_STANDARD, ts, self.options.time_quantum
            ):
                changed |= self._view_create(vname).set_bit(row_id, col)
        return changed

    def clear_bit(self, row_id: int, col: int) -> bool:
        """Clear in ALL views (field.go ClearBit clears time views too)."""
        changed = False
        with self._mu:
            views = list(self.views.values())
        for v in views:
            if v.name.startswith(VIEW_BSI_PREFIX):
                continue
            changed |= v.clear_bit(row_id, col)
        return changed

    def set_value(self, col: int, value: int) -> bool:
        """BSI write with auto bit-depth growth (field.go:1075 SetValue)."""
        if self.options.type != FIELD_TYPE_INT:
            raise ValueError(f"field {self.name} is not an int field")
        if value < self.options.min:
            raise ValueError(f"value {value} below field minimum {self.options.min}")
        if value > self.options.max:
            raise ValueError(f"value {value} above field maximum {self.options.max}")
        base_value = value - self.options.base
        required = bit_depth_of(abs(base_value))
        if required > self.options.bit_depth:
            with self._mu:
                self.options.bit_depth = required
                self.save_meta()
        v = self._view_create(self.bsi_view_name())
        return v.set_value(col, self.options.bit_depth, base_value)

    def clear_value(self, col: int) -> bool:
        v = self.view(self.bsi_view_name())
        if v is None:
            return False
        val, exists = v.value(col, self.options.bit_depth)
        if not exists:
            return False
        return v.set_value(col, self.options.bit_depth, val, clear=True)

    def import_bits(
        self,
        row_ids: np.ndarray,
        cols: np.ndarray,
        timestamps: Optional[List[Optional[datetime]]] = None,
        clear: bool = False,
    ) -> None:
        """Bulk import grouped by view and shard (field.go:1204 Import).

        Non-mutex SET imports take the staged fast path: the whole batch
        is converted to fragment positions with three vector ops and
        routed by View.stage_bulk (one argsort, per-shard views, batched
        WAL framing + device invalidation); the per-row merge and rank-
        cache reconciliation are deferred to the next read barrier.
        Clears, mutex/bool fields and time views keep the exact per-
        fragment path (last-write-wins and changed-count semantics need
        the merge at apply time)."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.uint64)
        # shifts, not div/mod: SHARD_WIDTH is a power of two and the two
        # extra vector passes are measurable at bulk-ingest rates
        shards = cols >> np.uint64(SHARD_WIDTH_EXPONENT)

        # ONE group-commit round per call, covering the standard view AND
        # every time view it fans into (nested barriers — stage_bulk's,
        # bulk_import's mutex path — fold into this outermost one): a
        # timestamped import must not pay two sequential fsync rounds
        with walmod.GROUP_COMMIT.barrier():
            # standard view — one argsort groups the batch by shard
            # (utils/arrays.group_slices; a mask per shard would rescan
            # the whole batch n_shards times)
            if not self.options.no_standard_view:
                std = self._view_create(VIEW_STANDARD)
                if not clear and self.options.type not in (
                    FIELD_TYPE_MUTEX,
                    FIELD_TYPE_BOOL,
                ):
                    positions = (row_ids << np.uint64(SHARD_WIDTH_EXPONENT)) | (
                        cols & np.uint64(SHARD_WIDTH - 1)
                    )
                    std.stage_bulk(shards, positions)
                else:
                    # per-shard exact imports coalesce into the same
                    # round (clears/mutex still fsync-strict, just not
                    # once per shard)
                    for shard, sl in group_slices(shards):
                        std.fragment(int(shard)).bulk_import(
                            row_ids[sl], cols[sl], clear=clear
                        )

            # time views
            if timestamps is not None and self.options.time_quantum:
                by_view: Dict[str, List[int]] = {}
                for i, ts in enumerate(timestamps):
                    if ts is None:
                        continue
                    for vname in timeq.views_by_time(
                        VIEW_STANDARD, ts, self.options.time_quantum
                    ):
                        by_view.setdefault(vname, []).append(i)
                for vname, idxs in by_view.items():
                    v = self._view_create(vname)
                    idx = np.array(idxs)
                    for shard, sl in group_slices(shards[idx]):
                        m = idx[sl]
                        v.fragment(int(shard)).bulk_import(row_ids[m], cols[m], clear=clear)

    def import_row_words(self, row_id: int, shard: int, words: np.ndarray) -> int:
        """Word-level bulk union of one row of one shard (standard view);
        see Fragment.import_row_words. Returns newly-set bit count."""
        if self.options.type not in (FIELD_TYPE_SET, FIELD_TYPE_TIME, FIELD_TYPE_BOOL):
            raise ValueError(
                f"word-level import not supported on {self.options.type} fields"
            )
        std = self._view_create(VIEW_STANDARD)
        return std.fragment(int(shard)).import_row_words(row_id, words)

    def import_values(self, cols: np.ndarray, values: np.ndarray) -> None:
        """Bulk BSI import (field.go:1285 importValue)."""
        cols = np.asarray(cols, dtype=np.uint64)
        values = np.asarray(values, dtype=np.int64)
        if len(values) and (
            values.min() < self.options.min or values.max() > self.options.max
        ):
            raise ValueError("value out of field min/max range")
        base_values = values - self.options.base
        required = int(
            max(bit_depth_of(int(np.abs(base_values).max())) if len(values) else 1, 1)
        )
        if required > self.options.bit_depth:
            with self._mu:
                self.options.bit_depth = required
                self.save_meta()
        v = self._view_create(self.bsi_view_name())
        shards = cols // SHARD_WIDTH
        with walmod.GROUP_COMMIT.barrier():
            for shard, m in group_slices(shards):
                v.fragment(int(shard)).import_values(
                    cols[m], base_values[m], self.options.bit_depth
                )

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def value(self, col: int) -> Tuple[int, bool]:
        """(value, exists) for one column (field.go:1040 Value)."""
        v = self.view(self.bsi_view_name())
        if v is None:
            return 0, False
        val, exists = v.value(col, self.options.bit_depth)
        if not exists:
            return 0, False
        return val + self.options.base, True

    def row_positions(self, row_id: int) -> np.ndarray:
        v = self.view(VIEW_STANDARD)
        return v.row_positions(row_id) if v is not None else np.empty(0, np.uint64)

    def bsi_group(self):
        """The field's own BSI group descriptor (field.go bsiGroup(f.name))."""
        o = self.options
        return o.base, o.bit_depth, o.min, o.max

    # baseValue adjustment for range predicates (field.go:1583 baseValue).
    def base_value(self, op: str, value: int) -> Tuple[int, bool]:
        o = self.options
        depth_min = o.base - (1 << o.bit_depth) + 1
        depth_max = o.base + (1 << o.bit_depth) - 1
        if op in ("gt", "gte"):
            if value > depth_max:
                return 0, True
            if value > depth_min:
                return value - o.base, False
            return 0, False
        if op in ("lt", "lte"):
            if value < depth_min:
                return 0, True
            if value > depth_max:
                return depth_max - o.base, False
            return value - o.base, False
        if op in ("eq", "neq"):
            if value < depth_min or value > depth_max:
                return 0, True
            return value - o.base, False
        raise ValueError(f"invalid op {op}")

    def base_value_between(self, lo: int, hi: int) -> Tuple[int, int, bool]:
        o = self.options
        depth_min = o.base - (1 << o.bit_depth) + 1
        depth_max = o.base + (1 << o.bit_depth) - 1
        if hi < depth_min or lo > depth_max:
            return 0, 0, True
        lo = max(lo, depth_min)
        hi = min(hi, depth_max)
        return lo - o.base, hi - o.base, False
