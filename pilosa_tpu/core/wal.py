"""Fragment persistence: snapshot files + append-only op log (WAL).

Reference model: a fragment persists as a full roaring serialization with ops
appended after the snapshot section, replayed on open (fragment.go:311-458
openStorage, roaring.go:4662-4692 op apply, writeOp at :1612). Crash safety
comes from temp-file + atomic rename (.snapshotting/.temp extensions,
fragment.go:68-78).

Here the snapshot is our own dense-block dialect (the roaring interchange
format lives separately in core/roaring_io.py for import/export compat), and
the WAL is a separate sidecar file of batched set/clear records, each
CRC-guarded so a torn tail is detected and discarded on replay.

Snapshot file (.snap):
    magic  b"PTSNAP01"
    u64 shard, u64 n_bits, u64 n_rows
    n_rows * ( u64 row_id, u8 rep, u64 n_items, payload uint32[n_items] )

WAL file (.wal), per record:
    u32 magic 0x5054574C ("PTWL"), u8 op (0=set 1=clear), u32 n,
    u32 crc32(payload), payload = uint64[n] fragment positions
"""

from __future__ import annotations

import os
import struct
import zlib
from collections import OrderedDict
from contextlib import contextmanager, nullcontext
from typing import Dict, Iterator, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.core.rowstore import RowBits

SNAP_MAGIC = b"PTSNAP01"
WAL_MAGIC = 0x5054574C
OP_SET = 0
OP_CLEAR = 1
# Word-level row union (bulk ingest): payload[0] = row_id, payload[1:] = the
# row's dense uint32 words viewed as uint64 — one record per imported row.
OP_ROW_WORDS = 2

_REC_HDR = struct.Struct("<IBII")


def write_snapshot_stream(f, shard: int, n_bits: int, rows) -> None:
    """Write the snapshot record stream to an open binary file object.

    Single codec shared by on-disk snapshots and resize/backup streaming
    (reference: the same WriteTo serves both, fragment.go:2436). `rows` is
    any mapping row_id -> RowBits; a mapping exposing `rep_payload(row_id)`
    (the lazy snapshot tier) is serialized without materializing rows."""
    f.write(SNAP_MAGIC)
    f.write(struct.pack("<QQQ", shard, n_bits, len(rows)))
    rep_payload = getattr(rows, "rep_payload", None)
    bulk = getattr(rows, "bulk", None)
    with bulk() if bulk is not None else nullcontext():
        for row_id in sorted(rows):
            if rep_payload is not None:
                rep, payload = rep_payload(row_id)
            else:
                rb = rows[row_id]
                rep, payload = rb.rep(), rb.payload()
            f.write(struct.pack("<QBQ", row_id, rep, len(payload)))
            f.write(payload.astype(np.uint32, copy=False).tobytes())


def _read_exact(f, n: int) -> bytes:
    """Read exactly n bytes or raise — a truncated stream (torn network
    transfer, partial write) must fail loudly, never parse short."""
    data = f.read(n)
    if len(data) != n:
        raise ValueError(f"truncated snapshot stream: wanted {n} bytes, got {len(data)}")
    return data


def read_snapshot_stream(f) -> Tuple[int, int, Dict[int, RowBits]]:
    """Inverse of write_snapshot_stream; returns (shard, n_bits, rows)."""
    magic = _read_exact(f, 8)
    if magic != SNAP_MAGIC:
        raise ValueError(f"bad snapshot magic {magic!r}")
    shard, n_bits, n_rows = struct.unpack("<QQQ", _read_exact(f, 24))
    rows: Dict[int, RowBits] = {}
    for _ in range(n_rows):
        row_id, rep, n_items = struct.unpack("<QBQ", _read_exact(f, 17))
        payload = np.frombuffer(_read_exact(f, n_items * 4), dtype=np.uint32).copy()
        rows[row_id] = RowBits.from_payload(n_bits, rep, payload)
    return shard, n_bits, rows


def write_snapshot(path: str, shard: int, n_bits: int, rows: Dict[int, RowBits]) -> None:
    """Atomically write a full snapshot (temp file + rename)."""
    tmp = path + ".snapshotting"
    with open(tmp, "wb") as f:
        write_snapshot_stream(f, shard, n_bits, rows)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_snapshot(path: str) -> Tuple[int, int, Dict[int, RowBits]]:
    """Read a snapshot; returns (shard, n_bits, rows)."""
    with open(path, "rb") as f:
        return read_snapshot_stream(f)


def read_snapshot_index(path: str) -> Tuple[int, int, Dict[int, Tuple[int, int, int]]]:
    """Header-only snapshot scan: (shard, n_bits, index) where
    index[row_id] = (rep, payload_byte_offset, n_items). Payloads are
    seeked over, not read — the lazy host tier's open cost is O(rows), not
    O(bits) (the host analog of the reference's mmap open,
    fragment.go:311 openStorage)."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        magic = _read_exact(f, 8)
        if magic != SNAP_MAGIC:
            raise ValueError(f"bad snapshot magic {magic!r}")
        shard, n_bits, n_rows = struct.unpack("<QQQ", _read_exact(f, 24))
        index: Dict[int, Tuple[int, int, int]] = {}
        pos = 32
        for _ in range(n_rows):
            f.seek(pos)
            row_id, rep, n_items = struct.unpack("<QBQ", _read_exact(f, 17))
            payload_off = pos + 17
            if payload_off + n_items * 4 > size:
                raise ValueError("truncated snapshot: payload overruns file")
            index[row_id] = (rep, payload_off, n_items)
            pos = payload_off + n_items * 4
    return shard, n_bits, index


# Open-WAL-handle cap: a holder with thousands of fragments must not hold
# thousands of fds (the reference caps open files via syswrap,
# syswrap/file.go + max-file-count config). Writers above the cap close
# their fd LRU-style and transparently reopen in append mode on next use.
_MAX_OPEN_WALS = max(8, int(os.environ.get("PILOSA_TPU_MAX_OPEN_FILES", "256")))


class WalWriter:
    """Append-only op log. One writer per fragment (single-writer, like the
    reference's per-fragment storage lock); file handles are pooled under
    _MAX_OPEN_WALS."""

    _lru: "OrderedDict[int, WalWriter]" = OrderedDict()
    _lru_mu = TrackedLock("wal.lru_mu")
    _next_tok = 0

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._pinned = 0  # guarded by _lru_mu; evictor skips pinned fds
        self._closed = False
        with WalWriter._lru_mu:
            WalWriter._next_tok += 1
            self._tok = WalWriter._next_tok
        with self._pin():  # fail at construction if the path is bad
            pass

    @contextmanager
    def _pin(self):
        """Open (or touch) this writer's fd and hold it safe from LRU
        eviction for the duration — a concurrent writer's eviction pass
        must never close an fd mid-write. Victim fds are closed OUTSIDE
        the lock so eviction I/O never stalls other writers."""
        to_close = []
        with WalWriter._lru_mu:
            if self._closed:
                # LRU-evicted fds reopen transparently, but a CLOSED writer
                # must not resurrect its WAL file (a racing late write
                # after fragment close/delete would silently recreate it)
                raise ValueError(f"WalWriter for {self.path} is closed")
            if self._f is None:
                self._f = open(self.path, "ab")
            WalWriter._lru[self._tok] = self
            WalWriter._lru.move_to_end(self._tok)
            self._pinned += 1
            # detach oldest UNPINNED fds over the cap
            excess = len(WalWriter._lru) - _MAX_OPEN_WALS
            if excess > 0:
                for tok in list(WalWriter._lru):
                    if excess <= 0:
                        break
                    victim = WalWriter._lru[tok]
                    if victim._pinned:
                        continue
                    del WalWriter._lru[tok]
                    if victim._f is not None:
                        to_close.append(victim._f)
                        victim._f = None
                    excess -= 1
            f = self._f
        for fh in to_close:
            fh.close()
        try:
            yield f
        finally:
            with WalWriter._lru_mu:
                self._pinned -= 1

    def append(self, op: int, positions: np.ndarray) -> None:
        payload = np.asarray(positions, dtype=np.uint64).tobytes()
        rec = _REC_HDR.pack(WAL_MAGIC, op, len(positions), zlib.crc32(payload))
        with self._pin() as f:
            f.write(rec + payload)
            f.flush()

    def append_many(self, records) -> None:
        """Frame a batch of (op, positions) records and land them with ONE
        write + flush — an import call's set AND clear records hit the
        file together instead of interleaving two syscall round-trips
        with the apply. Each record keeps its own CRC, so replay-side
        torn-tail handling is unchanged (the batch just tears as a unit
        or between records)."""
        data = encode_records(records)
        if not data:
            return
        with self._pin() as f:
            f.write(data)
            f.flush()

    def truncate(self) -> None:
        """Reset after a snapshot has absorbed all ops."""
        with self._pin() as f:
            f.truncate(0)
            f.seek(0)

    def close(self) -> None:
        with WalWriter._lru_mu:
            self._closed = True
            WalWriter._lru.pop(self._tok, None)
            if self._f is not None:
                self._f.close()
                self._f = None


def encode_records(records) -> bytes:
    """Frame a batch of (op, positions) records with the WAL record codec
    into one byte string. This is also the WIRE format live-resize delta
    shipping uses (core/fragment.py drain_capture -> apply_transfer_records):
    both ends share the on-disk log's CRC framing, so there is exactly one
    record codec to keep correct."""
    bufs = []
    for op, positions in records:
        payload = np.asarray(positions, dtype=np.uint64).tobytes()
        bufs.append(
            _REC_HDR.pack(WAL_MAGIC, op, len(positions), zlib.crc32(payload))
        )
        bufs.append(payload)
    return b"".join(bufs)


def decode_records(data: bytes) -> Iterator[Tuple[int, np.ndarray]]:
    """Inverse of encode_records. STRICT, unlike on-disk replay: a torn
    network transfer must fail the transfer leg loudly (the client retries
    it), never silently apply a prefix of the delta — on disk a torn tail
    is the expected kill-9 artifact, on the wire it is data loss."""
    pos = 0
    n_total = len(data)
    while pos < n_total:
        if pos + _REC_HDR.size > n_total:
            raise ValueError("truncated delta stream: partial record header")
        magic, op, n, crc = _REC_HDR.unpack_from(data, pos)
        pos += _REC_HDR.size
        if magic != WAL_MAGIC:
            raise ValueError(
                f"bad delta record magic at offset {pos - _REC_HDR.size}"
            )
        end = pos + n * 8
        if end > n_total:
            raise ValueError("truncated delta stream: partial payload")
        payload = data[pos:end]
        if zlib.crc32(payload) != crc:
            raise ValueError(f"delta record CRC mismatch at offset {pos}")
        yield op, np.frombuffer(payload, dtype=np.uint64)
        pos = end


def replay_wal(path: str) -> Iterator[Tuple[int, np.ndarray]]:
    """Yield (op, positions) records; stops cleanly at a torn/corrupt tail."""
    for op, positions in _walk_wal(path):
        yield op, positions


def check_wal(path: str) -> Tuple[int, str, str]:
    """Integrity walk for `pilosa-tpu check`: returns (n_valid_ops, status,
    detail). status is one of:
    - "ok":   every byte is a valid record
    - "torn": the tail is an INCOMPLETE record (short header or short
              payload with a valid header) — the normal kill-9-mid-append
              case the replay path tolerates by design
    - "corrupt": a complete-looking record fails its magic or CRC check —
              data damage replay would silently discard"""
    n_ops = 0
    pos = 0
    for op, positions in _walk_wal(path):
        n_ops += 1
        pos += _REC_HDR.size + len(positions) * 8
    size = os.path.getsize(path) if os.path.exists(path) else 0
    rest = size - pos
    if rest == 0:
        return n_ops, "ok", ""
    with open(path, "rb") as f:
        f.seek(pos)
        tail = f.read(_REC_HDR.size)
    if len(tail) < _REC_HDR.size:
        return n_ops, "torn", f"{rest}-byte partial header at tail"
    magic, op, n, crc = _REC_HDR.unpack(tail)
    if magic != WAL_MAGIC:
        return n_ops, "corrupt", f"bad record magic at offset {pos}"
    if rest < _REC_HDR.size + n * 8:
        return n_ops, "torn", f"partial payload at tail ({rest} bytes)"
    return n_ops, "corrupt", f"CRC mismatch at offset {pos}"


def _walk_wal(path: str) -> Iterator[Tuple[int, np.ndarray]]:
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_REC_HDR.size)
            if len(hdr) < _REC_HDR.size:
                return
            magic, op, n, crc = _REC_HDR.unpack(hdr)
            if magic != WAL_MAGIC:
                return
            payload = f.read(n * 8)
            if len(payload) < n * 8 or zlib.crc32(payload) != crc:
                return
            yield op, np.frombuffer(payload, dtype=np.uint64)
