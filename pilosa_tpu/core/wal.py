"""Fragment persistence: snapshot files + append-only op log (WAL).

Reference model: a fragment persists as a full roaring serialization with ops
appended after the snapshot section, replayed on open (fragment.go:311-458
openStorage, roaring.go:4662-4692 op apply, writeOp at :1612). Crash safety
comes from temp-file + atomic rename (.snapshotting/.temp extensions,
fragment.go:68-78).

Here the snapshot is our own dense-block dialect (the roaring interchange
format lives separately in core/roaring_io.py for import/export compat), and
the WAL is a separate sidecar file of batched set/clear records, each
CRC-guarded so a torn tail is detected and discarded on replay.

Snapshot file (.snap):
    magic  b"PTSNAP01"
    u64 shard, u64 n_bits, u64 n_rows
    n_rows * ( u64 row_id, u8 rep, u64 n_items, payload uint32[n_items] )

WAL file (.wal), per record:
    u32 magic 0x5054574C ("PTWL"), u8 op (0=set 1=clear), u32 n,
    u32 crc32(payload), payload = uint64[n] fragment positions

Durability model (ISSUE 12): an append is a buffered write+flush under
the writer's fd pin; the fsync that makes it crash-durable is a GROUP
COMMIT (`WalGroupCommit`): concurrent appenders mark their writers
dirty and `wait_durable` joins a leader/follower commit loop — the
first waiter becomes the leader, fsyncs EVERY dirty WAL in one round,
and releases the whole group, so N concurrent import calls pay ~one
fsync round between them instead of one each. `sync-interval` > 0
trades the wait away entirely: callers return after the buffered
write and a background syncer fsyncs on that cadence — an honest,
bounded crash-loss window (docs/configuration.md "Durability").
"""

from __future__ import annotations

import errno
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict
from contextlib import contextmanager, nullcontext
from typing import IO, Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedCondition, TrackedLock
from pilosa_tpu.utils.race import race_checked
from pilosa_tpu.core.rowstore import RowBits

SNAP_MAGIC = b"PTSNAP01"
WAL_MAGIC = 0x5054574C
OP_SET = 0
OP_CLEAR = 1
# Word-level row union (bulk ingest): payload[0] = row_id, payload[1:] = the
# row's dense uint32 words viewed as uint64 — one record per imported row.
OP_ROW_WORDS = 2

_REC_HDR = struct.Struct("<IBII")


# ---------------------------------------------------------------------------
# fault injection hook (server/faults.py FaultInjector installs itself
# here via install_injector — core must not import the server layer).
# Points: "wal.write" (before the framed bytes land), "wal.rollback"
# (before a failed append truncates back — failing it too poisons the
# writer), "wal.fsync" (per-file, inside a commit round), "wal.truncate"
# (before the post-truncate fsync), "wal.commit.pre_fsync" /
# "wal.commit.post_fsync"
# (around a whole group-commit round), "snapshot.pre_truncate"
# (fragment snapshot written, WAL not yet reset), "merge.install"
# (merge-barrier delta about to park). The hook may raise (ENOSPC /
# IO-error simulation), sleep, or SIGKILL the process (crash matrix).
# ---------------------------------------------------------------------------

_fault_hook: Optional[Callable[[str, str], None]] = None


class ShortWriteFault(Exception):
    """Raised by an injected fault hook to request a torn append: the
    writer lands a PREFIX of the framed bytes (the kill-9-mid-write
    artifact replay must tolerate), then fails the call with EIO."""


def set_fault_hook(fn: Optional[Callable[[str, str], None]]) -> None:
    global _fault_hook
    _fault_hook = fn


def fault_point(point: str, path: str = "") -> None:
    hook = _fault_hook
    if hook is not None:
        hook(point, path)


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-created (or renamed-into-place) entry
    survives a crash — fsyncing the file itself does not persist its
    directory entry. Best-effort: platforms without O_RDONLY directory
    fds simply skip it."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_snapshot_stream(f: IO[bytes], shard: int, n_bits: int, rows: Any) -> None:
    """Write the snapshot record stream to an open binary file object.

    Single codec shared by on-disk snapshots and resize/backup streaming
    (reference: the same WriteTo serves both, fragment.go:2436). `rows` is
    any mapping row_id -> RowBits; a mapping exposing `rep_payload(row_id)`
    (the lazy snapshot tier) is serialized without materializing rows."""
    f.write(SNAP_MAGIC)
    f.write(struct.pack("<QQQ", shard, n_bits, len(rows)))
    rep_payload = getattr(rows, "rep_payload", None)
    bulk = getattr(rows, "bulk", None)
    with bulk() if bulk is not None else nullcontext():
        for row_id in sorted(rows):
            if rep_payload is not None:
                rep, payload = rep_payload(row_id)
            else:
                rb = rows[row_id]
                rep, payload = rb.rep(), rb.payload()
            f.write(struct.pack("<QBQ", row_id, rep, len(payload)))
            f.write(payload.astype(np.uint32, copy=False).tobytes())


def _read_exact(f: IO[bytes], n: int) -> bytes:
    """Read exactly n bytes or raise — a truncated stream (torn network
    transfer, partial write) must fail loudly, never parse short."""
    data = f.read(n)
    if len(data) != n:
        raise ValueError(f"truncated snapshot stream: wanted {n} bytes, got {len(data)}")
    return data


def read_snapshot_stream(f: IO[bytes]) -> Tuple[int, int, Dict[int, RowBits]]:
    """Inverse of write_snapshot_stream; returns (shard, n_bits, rows)."""
    magic = _read_exact(f, 8)
    if magic != SNAP_MAGIC:
        raise ValueError(f"bad snapshot magic {magic!r}")
    shard, n_bits, n_rows = struct.unpack("<QQQ", _read_exact(f, 24))
    rows: Dict[int, RowBits] = {}
    for _ in range(n_rows):
        row_id, rep, n_items = struct.unpack("<QBQ", _read_exact(f, 17))
        payload = np.frombuffer(_read_exact(f, n_items * 4), dtype=np.uint32).copy()
        rows[row_id] = RowBits.from_payload(n_bits, rep, payload)
    return shard, n_bits, rows


def write_snapshot(path: str, shard: int, n_bits: int, rows: Dict[int, RowBits]) -> None:
    """Atomically write a full snapshot (temp file + rename)."""
    tmp = path + ".snapshotting"
    with open(tmp, "wb") as f:
        write_snapshot_stream(f, shard, n_bits, rows)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # the rename is only durable once the directory entry is: without
    # this a crash can lose a just-written snapshot whose WAL was
    # already truncated against it
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def read_snapshot(path: str) -> Tuple[int, int, Dict[int, RowBits]]:
    """Read a snapshot; returns (shard, n_bits, rows)."""
    with open(path, "rb") as f:
        return read_snapshot_stream(f)


def read_snapshot_index(path: str) -> Tuple[int, int, Dict[int, Tuple[int, int, int]]]:
    """Header-only snapshot scan: (shard, n_bits, index) where
    index[row_id] = (rep, payload_byte_offset, n_items). Payloads are
    seeked over, not read — the lazy host tier's open cost is O(rows), not
    O(bits) (the host analog of the reference's mmap open,
    fragment.go:311 openStorage)."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        magic = _read_exact(f, 8)
        if magic != SNAP_MAGIC:
            raise ValueError(f"bad snapshot magic {magic!r}")
        shard, n_bits, n_rows = struct.unpack("<QQQ", _read_exact(f, 24))
        index: Dict[int, Tuple[int, int, int]] = {}
        pos = 32
        for _ in range(n_rows):
            f.seek(pos)
            row_id, rep, n_items = struct.unpack("<QBQ", _read_exact(f, 17))
            payload_off = pos + 17
            if payload_off + n_items * 4 > size:
                raise ValueError("truncated snapshot: payload overruns file")
            index[row_id] = (rep, payload_off, n_items)
            pos = payload_off + n_items * 4
    return shard, n_bits, index


# Open-WAL-handle cap: a holder with thousands of fragments must not hold
# thousands of fds (the reference caps open files via syswrap,
# syswrap/file.go + max-file-count config). Writers above the cap close
# their fd LRU-style and transparently reopen in append mode on next use.
_MAX_OPEN_WALS = max(8, int(os.environ.get("PILOSA_TPU_MAX_OPEN_FILES", "256")))


@race_checked(exclude=(
    # _closed is written under _lru_mu and read by a commit round under
    # commit_mu: a formally lock-free pair, made benign by the PR-11
    # close() fix (close fsyncs UNCONDITIONALLY, so a round that reads a
    # stale False and skips this writer can never ack unsynced bytes) —
    # tests/test_race.py reproduces the pre-fix ack race seeded-style.
    # _poisoned is single-writer state: fragment.mu serializes all
    # appends to one WAL, so only the owning writer thread reads/sets it.
    "_closed",
    "_poisoned",
))
class WalWriter:
    """Append-only op log. One writer per fragment (single-writer, like the
    reference's per-fragment storage lock); file handles are pooled under
    _MAX_OPEN_WALS."""

    _lru: "OrderedDict[int, WalWriter]" = OrderedDict()
    _lru_mu = TrackedLock("wal.lru_mu")
    _next_tok = 0

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._pinned = 0  # guarded by _lru_mu; evictor skips pinned fds
        self._closed = False
        self._poisoned = False  # un-rolled-back torn write: appends refuse
        with WalWriter._lru_mu:
            WalWriter._next_tok += 1
            self._tok = WalWriter._next_tok
        with self._pin():  # fail at construction if the path is bad
            pass

    @contextmanager
    def _pin(self) -> Iterator[IO[bytes]]:
        """Open (or touch) this writer's fd and hold it safe from LRU
        eviction for the duration — a concurrent writer's eviction pass
        must never close an fd mid-write. Victim fds are closed OUTSIDE
        the lock so eviction I/O never stalls other writers."""
        to_close = []
        sync_dir = None
        with WalWriter._lru_mu:
            if self._closed:
                # LRU-evicted fds reopen transparently, but a CLOSED writer
                # must not resurrect its WAL file (a racing late write
                # after fragment close/delete would silently recreate it)
                raise ValueError(f"WalWriter for {self.path} is closed")
            if self._f is None:
                created = not os.path.exists(self.path)
                self._f = open(self.path, "ab")
                if created:
                    # a brand-new log's directory entry must survive a
                    # crash: fsync the parent dir once at creation
                    # (outside the lock, below — dir I/O must not stall
                    # other writers)
                    sync_dir = os.path.dirname(os.path.abspath(self.path))
            WalWriter._lru[self._tok] = self
            WalWriter._lru.move_to_end(self._tok)
            self._pinned += 1
            # detach oldest UNPINNED fds over the cap
            excess = len(WalWriter._lru) - _MAX_OPEN_WALS
            if excess > 0:
                for tok in list(WalWriter._lru):
                    if excess <= 0:
                        break
                    victim = WalWriter._lru[tok]
                    if victim._pinned:
                        continue
                    del WalWriter._lru[tok]
                    if victim._f is not None:
                        to_close.append(victim._f)
                        victim._f = None
                    excess -= 1
            f = self._f
        if sync_dir is not None:
            _fsync_dir(sync_dir)
        for fh in to_close:
            fh.close()
        try:
            yield f
        finally:
            with WalWriter._lru_mu:
                self._pinned -= 1

    def _write_framed(self, data: bytes) -> Optional[int]:
        """Buffered write+flush of framed record bytes under the fd pin,
        then mark this writer dirty with the group committer. Returns
        the commit token the caller hands to
        `GROUP_COMMIT.wait_durable` once it is OUTSIDE any fragment
        lock — the wait is where concurrent appenders coalesce into one
        fsync round.

        A failed or torn write (ENOSPC, injected short write) is ROLLED
        BACK — the file truncates to the pre-append offset — so a later
        successful append can never land BEYOND an unreplayable tear
        (replay stops at the first bad record, which would silently
        discard acked bytes written after it). If the rollback itself
        fails, the writer POISONS: every subsequent append raises
        instead of acking bytes replay would drop."""
        if self._poisoned:
            raise ValueError(
                f"WAL {self.path} is poisoned: a torn write could not be "
                "rolled back, so further appends would be unreplayable"
            )
        with self._pin() as f:
            end0 = f.seek(0, os.SEEK_END)
            try:
                try:
                    fault_point("wal.write", self.path)
                except ShortWriteFault:
                    f.write(data[: max(1, len(data) // 2)])
                    f.flush()
                    raise OSError(
                        errno.EIO, "[injected] short write", self.path
                    ) from None
                f.write(data)
                f.flush()
            except Exception:
                try:
                    fault_point("wal.rollback", self.path)
                    f.truncate(end0)
                    f.seek(end0)
                except Exception:  # noqa: BLE001 - poison, re-raise original
                    self._poisoned = True
                raise
        return GROUP_COMMIT.mark_dirty(self)

    def append(self, op: int, positions: np.ndarray) -> Optional[int]:
        positions = np.asarray(positions, dtype=np.uint64)
        if not len(positions):
            # a zero-length record has nothing to replay; framing (and
            # flushing) it only burned a syscall round-trip per empty
            # batch and an empty-payload record on disk
            return None
        payload = positions.tobytes()
        rec = _REC_HDR.pack(WAL_MAGIC, op, len(positions), zlib.crc32(payload))
        return self._write_framed(rec + payload)

    def append_many(
        self, records: Iterable[Tuple[int, np.ndarray]]
    ) -> Optional[int]:
        """Frame a batch of (op, positions) records and land them with ONE
        write + flush — an import call's set AND clear records hit the
        file together instead of interleaving two syscall round-trips
        with the apply. Each record keeps its own CRC, so replay-side
        torn-tail handling is unchanged (the batch just tears as a unit
        or between records)."""
        data = encode_records(records)
        if not data:
            return None
        return self._write_framed(data)

    def _fsync(self) -> None:
        """fsync this writer's file — called by a group-commit round (the
        leader or the background syncer), never by appenders directly.
        Reopens transparently after an LRU fd eviction (fsync flushes
        the inode's data regardless of which fd wrote it); a CLOSED
        writer is a no-op — close() already synced its tail."""
        try:
            with self._pin() as f:
                fault_point("wal.fsync", self.path)
                os.fsync(f.fileno())
        except ValueError:
            return

    def truncate(self) -> None:
        """Reset after a snapshot has absorbed all ops. The truncation is
        fsynced HERE, not deferred to a commit round: the caller is
        about to trust the snapshot as the sole copy, and a crash must
        not resurrect the pre-snapshot tail from a lazily-persisted
        length change."""
        with self._pin() as f:
            f.truncate(0)
            f.seek(0)
            fault_point("wal.truncate", self.path)
            os.fsync(f.fileno())
        # pending dirty marks cover bytes the truncation just erased;
        # their content is durable via the snapshot, so drop the mark
        # instead of paying a dead fsync in the next round
        GROUP_COMMIT.forget(self)

    def close(self) -> None:
        GROUP_COMMIT.forget(self)
        with WalWriter._lru_mu:
            self._closed = True
            WalWriter._lru.pop(self._tok, None)
            f, self._f = self._f, None
        # fsync UNCONDITIONALLY, not only when the dirty mark was still
        # ours: an in-flight commit round may have already claimed the
        # mark, and once _closed is set its _fsync() skips this writer —
        # without the sync here that round would ack its waiters with
        # this file's tail never durably on disk
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
            except OSError:
                pass  # close is best-effort; open() replay re-checks
            f.close()
        elif os.path.exists(self.path):
            # fd was LRU-evicted, possibly with an unsynced tail: reopen
            # to sync (existence-guarded so a re-close after fragment
            # deletion cannot resurrect the removed file)
            try:
                with open(self.path, "ab") as f2:
                    os.fsync(f2.fileno())
            except OSError:
                pass


# ---------------------------------------------------------------------------
# group commit: the durability half of every append
# ---------------------------------------------------------------------------


class WalSyncError(OSError):
    """A group-commit fsync round failed (ENOSPC, I/O error): EVERY
    caller whose append rode that round gets this — the whole commit
    group fails loudly, no caller is ever acked on a partial sync."""


# Cumulative module counters (the bench and the coalescing acceptance
# test read deltas of these; the same numbers publish as wal.* gauges
# via NodeServer.publish_cache_gauges). Guarded by GROUP_COMMIT's lock.
STATS = {"commits": 0, "commit_groups": 0, "fsyncs": 0, "sync_failures": 0}


@race_checked(exclude=(
    # stats is wired once by NodeServer between construction and traffic
    # (init-before-publish); _syncer_wake is a threading.Event (its own
    # internal lock); _defer is a threading.local (per-thread by
    # construction — the barrier deferral is thread-confined state)
    "stats",
    "_syncer_wake",
    "_defer",
))
class WalGroupCommit:
    """Leader/follower group commit across every open WAL writer (the
    CountBatcher shape, applied to fsync): appenders buffer their framed
    records (`WalWriter._write_framed` marks the writer dirty and hands
    back a token), then `wait_durable(token)` — called OUTSIDE any
    fragment lock — either joins an in-flight round or becomes the
    leader that fsyncs every dirty file and releases the whole group.

    Modes (`sync-interval`, three-way-synced `[wal]` knob):
    - 0 (strict): every commit group fsyncs before any caller returns —
      an acked write is durable.
    - > 0 (bounded loss): `wait_durable` returns immediately; a
      background syncer fsyncs on the cadence. A crash loses at most
      the last `sync-interval` seconds of ACKED writes (the buffered
      bytes are in the OS page cache, so only a machine/kernel crash
      loses them — a process kill does not).

    `barrier()` coalesces a bulk call's many per-fragment waits into
    exactly one round at exit (thread-local deferral): a 100-shard
    import pays one group fsync, not 100.

    Process-global, like DEVICE_CACHE: WAL files belong to the process,
    not to one in-process NodeServer."""

    def __init__(self) -> None:
        self._mu = TrackedLock("wal.commit_mu")
        self._cv = TrackedCondition(self._mu, name="wal.commit_cv")
        self._dirty: "OrderedDict[int, WalWriter]" = OrderedDict()
        self._seq = 0  # tokens handed out (appends marked dirty)
        self._done = 0  # highest token durably resolved by a round
        self._leading = False  # exactly one round in flight
        # tokens in (_fail_lo, _fail_seq] rode a FAILED round and raise;
        # tokens at or below _fail_lo were durably resolved by earlier
        # successful rounds and must never be failed retroactively
        self._fail_lo = 0
        self._fail_seq = 0
        self._fail_exc: Optional[BaseException] = None
        self._sync_interval = 0.0
        self._syncer: Optional[threading.Thread] = None
        self._syncer_wake = threading.Event()
        self._oldest_mark: Optional[float] = None  # lag gauge (interval mode)
        self._defer = threading.local()
        self.stats: Any = None  # optional StatsClient (NodeServer wires its own)

    # -- configuration -----------------------------------------------------

    def configure(self, sync_interval: Optional[float] = None) -> None:
        """Install the server's [wal] knobs. Switching interval -> strict
        flushes outstanding buffered appends first, so the strict
        contract holds from this call on."""
        if sync_interval is None:
            return
        with self._mu:
            old = self._sync_interval
            self._sync_interval = max(0.0, float(sync_interval))
            new = self._sync_interval
        if new > 0:
            self._ensure_syncer()
            self._syncer_wake.set()
        elif old > 0:
            self._syncer_wake.set()  # syncer sees 0 and exits
            self.flush()

    def sync_interval(self) -> float:
        with self._mu:
            return self._sync_interval

    # -- append-side API ---------------------------------------------------

    def mark_dirty(self, writer: "WalWriter") -> int:
        with self._mu:
            self._dirty[writer._tok] = writer
            self._dirty.move_to_end(writer._tok)
            self._seq += 1
            if self._oldest_mark is None:
                self._oldest_mark = time.monotonic()
            STATS["commits"] += 1
            token = self._seq
            interval = self._sync_interval
        if interval > 0:
            self._ensure_syncer()
        return token

    def forget(self, writer: "WalWriter") -> bool:
        """Drop a writer's dirty mark (truncate fsynced it explicitly, or
        close is about to). Returns whether it was dirty. Waiters whose
        tokens covered this writer still resolve with the next round —
        their bytes are durable through the explicit fsync."""
        with self._mu:
            return self._dirty.pop(writer._tok, None) is not None

    def wait_durable(self, token: Optional[int] = None) -> None:
        """Block until `token` (None = everything appended so far) is
        durable — or return immediately in bounded-loss mode. Inside a
        `barrier()` the wait is deferred to the barrier exit."""
        if getattr(self._defer, "depth", 0):
            if token is None:
                with self._mu:
                    token = self._seq
            self._defer.token = max(getattr(self._defer, "token", 0), token)
            return
        with self._mu:
            if token is None:
                token = self._seq
            if token <= 0:
                return
            if self._sync_interval > 0:
                # bounded-loss cadence: the caller is acked on the
                # buffered write; the syncer fsyncs within the interval.
                # UNLESS the cadence is known-broken: acking while every
                # background round fails (ENOSPC) would make the
                # documented loss window unbounded and invisible
                if self._fail_exc is not None:
                    raise WalSyncError(
                        "WAL background sync is failing; refusing to ack "
                        f"writes on a broken cadence: {self._fail_exc}"
                    ) from self._fail_exc
                return
        self._wait_strict(token)

    @contextmanager
    def barrier(self) -> Iterator[None]:
        """Coalesce every wait_durable on this thread into ONE group
        commit at exit (bulk imports: N fragments, one fsync round).
        Nested barriers fold into the outermost."""
        d = getattr(self._defer, "depth", 0)
        self._defer.depth = d + 1
        try:
            yield
        finally:
            self._defer.depth = d
            if d == 0:
                token = getattr(self._defer, "token", 0)
                self._defer.token = 0
                if token:
                    self.wait_durable(token)

    def flush(self) -> None:
        """Force one commit round covering everything outstanding —
        including dirty bytes RETAINED by a failed round (shutdown,
        tests, strict-mode switchover, post-ENOSPC retry). Ignores the
        interval-mode early return."""
        with self._mu:
            while self._leading:
                self._cv.wait()
            if not self._dirty:
                return
            self._leading = True
        self._lead_round()
        with self._mu:
            self._check_failed_locked(self._done)

    # -- the commit loop ---------------------------------------------------

    def _wait_strict(self, token: int) -> None:
        with self._mu:
            while True:
                if self._done >= token:
                    # resolved: durably synced, or part of a failed
                    # round whose failure has not been retried away yet
                    self._check_failed_locked(token)
                    return
                if not self._leading:
                    self._leading = True
                    break
                self._cv.wait()
        self._lead_round()
        with self._mu:
            self._check_failed_locked(token)

    def _check_failed_locked(self, token: int) -> None:
        # only tokens inside the failed rounds' range raise: a token
        # already durably resolved by an EARLIER successful round must
        # not be failed retroactively (its write is on disk and applied)
        if (
            self._fail_exc is not None
            and self._fail_lo < token <= self._fail_seq
        ):
            raise WalSyncError(
                f"WAL group commit failed: {self._fail_exc}"
            ) from self._fail_exc

    def _lead_round(self) -> None:
        try:
            self._sync_round()
        finally:
            with self._mu:
                self._leading = False
                self._cv.notify_all()

    def _sync_round(self) -> None:
        with self._mu:
            batch = list(self._dirty.values())
            self._dirty.clear()
            top = self._seq
            prev_done = self._done
            oldest = self._oldest_mark
            self._oldest_mark = None
            stats = self.stats
        fault_point("wal.commit.pre_fsync")
        err: Optional[BaseException] = None
        n_synced = 0
        for w in batch:
            try:
                w._fsync()
                n_synced += 1
            except Exception as e:  # noqa: BLE001 - fails the whole group
                err = e
        fault_point("wal.commit.post_fsync")
        group = top - prev_done
        with self._mu:
            self._done = top
            if err is None:
                # a successful round re-synced any bytes a FAILED earlier
                # round retained as dirty: tokens still parked on that
                # failure are durable now, so the failure state clears —
                # only waiters who observed it before the retry raised
                # (correct: their durability genuinely had not happened)
                self._fail_exc = None
                self._fail_lo = 0
                self._fail_seq = 0
            if err is not None:
                # the WHOLE group fails loudly: every waiter with a
                # token in this round raises, and unsynced writers stay
                # dirty so a later round retries their bytes. Back-to-
                # back failures WIDEN the range (min) — retained bytes
                # from the first failure are still unsynced, so their
                # tokens must keep raising until a round succeeds
                self._fail_lo = (
                    min(self._fail_lo, prev_done)
                    if self._fail_exc is not None
                    else prev_done
                )
                self._fail_seq = top
                self._fail_exc = err
                STATS["sync_failures"] += 1
                for w in batch:
                    if not w._closed:
                        self._dirty.setdefault(w._tok, w)
                if self._dirty and self._oldest_mark is None:
                    self._oldest_mark = oldest
            if batch:
                STATS["commit_groups"] += 1
                STATS["fsyncs"] += n_synced
        # emissions OUTSIDE the lock: a statsd push under commit_mu
        # would serialize every appender behind the network. Only the
        # per-round distributions emit here — the cumulative
        # commit_groups/fsyncs totals publish as gauges at scrape time
        # (NodeServer.publish_cache_gauges), so each renders as exactly
        # one series
        if stats is not None and batch:
            stats.histogram("wal.group_size", float(max(group, 1)))
            if oldest is not None:
                stats.timing("wal.sync_lag_ms", time.monotonic() - oldest)

    # -- background syncer (interval mode) ---------------------------------

    def _ensure_syncer(self) -> None:
        with self._mu:
            if self._syncer is not None and self._syncer.is_alive():
                return
            t = threading.Thread(
                target=self._syncer_loop,
                name="pilosa-tpu-wal-sync",
                daemon=True,
            )
            self._syncer = t
            # started under the lock: a concurrent caller checking
            # is_alive() on a created-but-unstarted thread would spawn a
            # duplicate syncer (two competing fsync cadences, one orphan)
            t.start()

    def _syncer_loop(self) -> None:
        while True:
            with self._mu:
                interval = self._sync_interval
            if interval <= 0:
                return
            self._syncer_wake.wait(interval)
            self._syncer_wake.clear()
            with self._mu:
                if self._sync_interval <= 0:
                    return
                if self._leading or not self._dirty:
                    continue
                self._leading = True
            try:
                self._lead_round()
            except Exception:  # noqa: BLE001 - keep the cadence alive
                pass


GROUP_COMMIT = WalGroupCommit()


def stats_snapshot() -> Dict[str, int]:
    """wal.* gauge values (NodeServer.publish_cache_gauges)."""
    with GROUP_COMMIT._mu:
        return dict(STATS)


def encode_records(records: Iterable[Tuple[int, np.ndarray]]) -> bytes:
    """Frame a batch of (op, positions) records with the WAL record codec
    into one byte string. This is also the WIRE format live-resize delta
    shipping uses (core/fragment.py drain_capture -> apply_transfer_records):
    both ends share the on-disk log's CRC framing, so there is exactly one
    record codec to keep correct. Zero-length records are skipped — they
    carry nothing to replay (or to apply on the wire) and an empty SET
    batch must not cost a framed record."""
    bufs = []
    for op, positions in records:
        if not len(positions):
            continue
        payload = np.asarray(positions, dtype=np.uint64).tobytes()
        bufs.append(
            _REC_HDR.pack(WAL_MAGIC, op, len(positions), zlib.crc32(payload))
        )
        bufs.append(payload)
    return b"".join(bufs)


def decode_records(data: bytes) -> Iterator[Tuple[int, np.ndarray]]:
    """Inverse of encode_records. STRICT, unlike on-disk replay: a torn
    network transfer must fail the transfer leg loudly (the client retries
    it), never silently apply a prefix of the delta — on disk a torn tail
    is the expected kill-9 artifact, on the wire it is data loss."""
    pos = 0
    n_total = len(data)
    while pos < n_total:
        if pos + _REC_HDR.size > n_total:
            raise ValueError("truncated delta stream: partial record header")
        magic, op, n, crc = _REC_HDR.unpack_from(data, pos)
        pos += _REC_HDR.size
        if magic != WAL_MAGIC:
            raise ValueError(
                f"bad delta record magic at offset {pos - _REC_HDR.size}"
            )
        end = pos + n * 8
        if end > n_total:
            raise ValueError("truncated delta stream: partial payload")
        payload = data[pos:end]
        if zlib.crc32(payload) != crc:
            raise ValueError(f"delta record CRC mismatch at offset {pos}")
        yield op, np.frombuffer(payload, dtype=np.uint64)
        pos = end


def replay_wal(path: str) -> Iterator[Tuple[int, np.ndarray]]:
    """Yield (op, positions) records; stops cleanly at a torn/corrupt tail."""
    for op, positions in _walk_wal(path):
        yield op, positions


def check_wal(path: str) -> Tuple[int, str, str]:
    """Integrity walk for `pilosa-tpu check`: returns (n_valid_ops, status,
    detail). status is one of:
    - "ok":   every byte is a valid record
    - "torn": the tail is an INCOMPLETE record (short header or short
              payload with a valid header) — the normal kill-9-mid-append
              case the replay path tolerates by design
    - "corrupt": a complete-looking record fails its magic or CRC check —
              data damage replay would silently discard"""
    n_ops = 0
    pos = 0
    for op, positions in _walk_wal(path):
        n_ops += 1
        pos += _REC_HDR.size + len(positions) * 8
    size = os.path.getsize(path) if os.path.exists(path) else 0
    rest = size - pos
    if rest == 0:
        return n_ops, "ok", ""
    with open(path, "rb") as f:
        f.seek(pos)
        tail = f.read(_REC_HDR.size)
    if len(tail) < _REC_HDR.size:
        return n_ops, "torn", f"{rest}-byte partial header at tail"
    magic, op, n, crc = _REC_HDR.unpack(tail)
    if magic != WAL_MAGIC:
        return n_ops, "corrupt", f"bad record magic at offset {pos}"
    if rest < _REC_HDR.size + n * 8:
        return n_ops, "torn", f"partial payload at tail ({rest} bytes)"
    return n_ops, "corrupt", f"CRC mismatch at offset {pos}"


def _walk_wal(path: str) -> Iterator[Tuple[int, np.ndarray]]:
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_REC_HDR.size)
            if len(hdr) < _REC_HDR.size:
                return
            magic, op, n, crc = _REC_HDR.unpack(hdr)
            if magic != WAL_MAGIC:
                return
            payload = f.read(n * 8)
            if len(payload) < n * 8 or zlib.crc32(payload) != crc:
                return
            yield op, np.frombuffer(payload, dtype=np.uint64)
