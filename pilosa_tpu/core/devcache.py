"""Budgeted LRU cache for device-resident (HBM) arrays.

The reference bounds storage residency with mmap + explicit resource caps
(/root/reference/roaring.go:1437 RemapRoaringStorage, syswrap/mmap.go map
count caps): hot data lives in the page cache, cold data is a page fault
away. On TPU the analog is HBM residency: every row/stack a query touches
is device_put into HBM and should stay there while hot — but HBM is a fixed
budget, so residency must be *bounded* and cold entries must fall back to
the host store (a rebuild away, as a page fault is in the reference).

One process-global DeviceCache instance backs:
- Fragment per-row device arrays (core/fragment.py row_device), and
- View-level multi-shard row stacks (core/view.py row_stack),
so the budget is enforced jointly across all fragments and stacks.

Keys are (owner, *rest) tuples where `owner` is a per-object token from
`new_owner_token()`; `invalidate_owner` drops everything an object cached
(fragment close / replace-from-stream).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Callable, Dict, Hashable, Set, Tuple

from pilosa_tpu.utils.locks import TrackedLock

_DEFAULT_BUDGET_MB = 4096


def _env_budget_bytes() -> int:
    mb = os.environ.get("PILOSA_TPU_HBM_BUDGET_MB")
    try:
        mb = int(mb) if mb else _DEFAULT_BUDGET_MB
    except ValueError:
        mb = _DEFAULT_BUDGET_MB
    return mb * 1024 * 1024


_token_lock = TrackedLock("devcache.token_lock")
_token_next = 0


def new_owner_token() -> int:
    """Process-unique owner id (object identity is not reuse-safe)."""
    global _token_next
    with _token_lock:
        _token_next += 1
        return _token_next


def _nbytes(arr) -> int:
    nb = getattr(arr, "nbytes", None)
    if nb is not None:
        return int(nb)
    import numpy as np

    return int(np.asarray(arr).nbytes)


class DeviceCache:
    """LRU key -> device array map with a byte budget.

    A single entry larger than the whole budget is still admitted (the query
    needs it to run) but is evicted as soon as anything else is inserted —
    the budget bounds *steady-state* residency.
    """

    def __init__(self, budget_bytes: int | None = None):
        self._mu = TrackedLock("devcache.mu")
        self._entries: "OrderedDict[Tuple, object]" = OrderedDict()
        self._sizes: Dict[Tuple, int] = {}
        self._by_owner: Dict[Hashable, Set[Tuple]] = {}
        self._bytes = 0
        self.budget_bytes = (
            budget_bytes if budget_bytes is not None else _env_budget_bytes()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- core --------------------------------------------------------------

    def get(self, key: Tuple):
        with self._mu:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return arr

    def put(self, key: Tuple, arr) -> None:
        nb = _nbytes(arr)
        with self._mu:
            if key in self._entries:
                self._drop_locked(key)
            self._entries[key] = arr
            self._sizes[key] = nb
            self._by_owner.setdefault(key[0], set()).add(key)
            self._bytes += nb
            self._evict_locked(keep=key)

    def get_or_build(self, key: Tuple, build: Callable[[], object]):
        arr = self.get(key)
        if arr is None:
            arr = build()
            self.put(key, arr)
        return arr

    def invalidate(self, key: Tuple) -> None:
        with self._mu:
            if key in self._entries:
                self._drop_locked(key)

    def invalidate_owner(self, owner: Hashable) -> None:
        with self._mu:
            for key in list(self._by_owner.get(owner, ())):
                self._drop_locked(key)

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._sizes.clear()
            self._by_owner.clear()
            self._bytes = 0

    # -- internals ---------------------------------------------------------

    def _drop_locked(self, key: Tuple) -> None:
        self._entries.pop(key, None)
        self._bytes -= self._sizes.pop(key, 0)
        owner_keys = self._by_owner.get(key[0])
        if owner_keys is not None:
            owner_keys.discard(key)
            if not owner_keys:
                del self._by_owner[key[0]]

    def _evict_locked(self, keep: Tuple) -> None:
        while self._bytes > self.budget_bytes and len(self._entries) > 1:
            key = next(iter(self._entries))
            if key == keep:
                # the just-inserted entry is the only way to finish the
                # current query; evict around it
                self._entries.move_to_end(key)
                key = next(iter(self._entries))
                if key == keep:
                    break
            self._drop_locked(key)
            self.evictions += 1

    # -- introspection -----------------------------------------------------

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def stats_snapshot(self) -> Dict[str, int]:
        """One consistent view of the residency counters (exported as
        gauges on /metrics and /debug/vars by NodeServer)."""
        with self._mu:
            return {
                "resident_bytes": self._bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "hits": self.hits,
                "misses": self.misses,
                "budget_bytes": self.budget_bytes,
            }


# Process-global instance shared by fragments and views. Tests may swap the
# budget (set_budget) or replace the instance outright.
DEVICE_CACHE = DeviceCache()


def set_budget(budget_bytes: int) -> None:
    DEVICE_CACHE.budget_bytes = budget_bytes
