"""Budgeted LRU cache for device-resident (HBM) arrays — the extent store.

The reference bounds storage residency with mmap + explicit resource caps
(/root/reference/roaring.go:1437 RemapRoaringStorage, syswrap/mmap.go map
count caps): hot data lives in the page cache, cold data is a page fault
away. On TPU the analog is HBM residency: every row/stack a query touches
is device_put into HBM and should stay there while hot — but HBM is a fixed
budget, so residency must be *bounded* and cold entries must fall back to
the host store (a rebuild away, as a page fault is in the reference).

One process-global DeviceCache instance backs:
- Fragment per-row device arrays (core/fragment.py row_device),
- View-level multi-shard row stacks (core/view.py row_stack), and
- Operand EXTENTS (pilosa_tpu/hbm/residency.py): shard-major slices of a
  stacked operand, individually tracked so an HBM budget below one query's
  working set evicts and re-stages *slices*, not whole stacks,
so the budget is enforced jointly across all fragments, stacks and extents.

Keys are (owner, *rest) tuples where `owner` is a per-object token from
`new_owner_token()`; `invalidate_owner` drops everything an object cached
(fragment close / replace-from-stream).

Three properties the hbm/ residency layer leans on:

- get_or_build is SINGLE-FLIGHT: concurrent callers of the same key run
  exactly one build; the rest wait and share the result (a thundering herd
  of identical device_puts would overshoot the byte ledger and waste PCIe).
- Entries can be PINNED (refcounted): a pinned entry is never evicted —
  eviction is deferred until unpin — so an extent in use by an in-flight
  compiled dispatch cannot be dropped mid-query. Explicit invalidation of
  a pinned entry removes it from lookup immediately (new queries rebuild
  under the new version key) but its bytes stay on the ledger until the
  last unpin, because the device memory genuinely is still held by the
  in-flight operand ("zombie" bytes).
- `pin_timeout` is a leak safety valve: a pin held longer than the timeout
  (default: forever disabled here; the server wires hbm-pin-timeout) is
  forcibly released by the evictor, so a leaked pin degrades to an
  eviction, never to a permanently wedged budget.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import (
    Callable,
    Dict,
    Hashable,
    Iterable,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

from pilosa_tpu.utils import resources
from pilosa_tpu.utils.locks import TrackedCondition, TrackedLock
from pilosa_tpu.utils.race import race_checked

_DEFAULT_BUDGET_MB = 4096


def _env_budget_bytes() -> int:
    raw = os.environ.get("PILOSA_TPU_HBM_BUDGET_MB")
    try:
        mb = int(raw) if raw else _DEFAULT_BUDGET_MB
    except ValueError:
        mb = _DEFAULT_BUDGET_MB
    return mb * 1024 * 1024


_token_lock = TrackedLock("devcache.token_lock")
_token_next = 0


def new_owner_token() -> int:
    """Process-unique owner id (object identity is not reuse-safe)."""
    global _token_next
    with _token_lock:
        _token_next += 1
        return _token_next


def _nbytes(arr: object) -> int:
    nb = getattr(arr, "nbytes", None)
    if nb is not None:
        return int(nb)
    import numpy as np

    return int(np.asarray(arr).nbytes)


@race_checked(exclude=(
    # budget_bytes / pin_timeout are operator knobs written by
    # set_budget()/NodeServer configuration and read inside _mu holds;
    # a torn read is impossible (int/float) and a stale one only delays
    # an eviction by one pass. The stats counters are read lock-free by
    # gauge snapshots on purpose (monotonic, GIL-atomic int adds).
    "budget_bytes",
    "pin_timeout",
    "hits",
    "misses",
    "evictions",
    "evicted_extent_bytes",
    "stale_pin_reclaims",
    "quota_evictions",
))
class DeviceCache:
    """LRU key -> device array map with a byte budget.

    A single entry larger than the whole budget is still admitted (the query
    needs it to run) but is evicted as soon as anything else is inserted —
    the budget bounds *steady-state* residency. Likewise, when every entry
    is pinned the cache may sit over budget transiently; eviction resumes
    as pins release.
    """

    def __init__(
        self,
        budget_bytes: int | None = None,
        pin_timeout: float = 0.0,  # seconds; 0 = stale-pin reclaim off
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._mu = TrackedLock("devcache.mu")
        # single-flight get_or_build: waiters park here while a peer builds
        self._build_cv = TrackedCondition(self._mu, name="devcache.build_cv")
        self._building: Set[Tuple] = set()
        self._entries: "OrderedDict[Tuple, object]" = OrderedDict()
        self._sizes: Dict[Tuple, int] = {}
        self._by_owner: Dict[Hashable, Set[Tuple]] = {}
        self._bytes = 0
        # pin refcounts + first-pin time (for the stale-pin safety valve)
        self._pins: Dict[Tuple, int] = {}
        self._pin_t0: Dict[Tuple, float] = {}
        # invalidated-while-pinned entries: gone from lookup, bytes still
        # on the ledger until the last unpin releases the device memory
        self._zombies: Dict[Tuple, int] = {}
        # operand extents (hbm/residency.py) are flagged at insert so the
        # hbm.* gauges can report them separately from per-row entries
        self._extent_keys: Set[Tuple] = set()
        # shard coverage per key (hbm staging registers the shard span an
        # extent covers): invalidate_owner_shard drops only the entries
        # whose coverage contains the dirty shard — entries with no
        # recorded coverage are dropped conservatively
        self._cover: Dict[Tuple, frozenset] = {}
        # per-index attribution: insert sites tag each entry with the
        # index that owns it (fragment rows, view stacks, hbm extents all
        # know their index name); entries staged outside any index fall
        # into the "-" bucket so index_resident_bytes() always sums to
        # the global ledger byte-for-byte. The map lives and dies with
        # the entry (zombie bytes keep theirs until the last unpin), so
        # index churn cannot leak attribution state.
        self._key_index: Dict[Tuple, str] = {}
        # eviction-deferral sessions (deferred_eviction): while a query's
        # lowering stages its operand set, evicting to make room for
        # operand K must not take operand K+1's resident extents — LRU's
        # cyclic-scan cascade would re-upload the whole working set every
        # query, the exact cliff extents exist to remove. Residency may
        # transiently exceed the budget up to the query's working set
        # (the same overshoot the oversized-entry rule already allows);
        # the ledger settles back under budget when the session ends.
        self._defer_evict = 0
        # per-index (tenant) residency quotas: 0 / absent = unlimited.
        # Enforced by _evict_locked — eviction pressure lands on the
        # over-quota owner FIRST (its own LRU order), and an index stays
        # within its quota even when the global budget has room, so
        # tenant A's warm extents survive tenant B's flood. Configured
        # by NodeServer from the [tenants] section (configure_quotas).
        self._index_quota_default = 0
        self._index_quota: Dict[str, int] = {}
        self._quota_evictions_index: Dict[str, int] = {}
        self.pin_timeout = pin_timeout
        self._clock = clock
        self.budget_bytes = (
            budget_bytes if budget_bytes is not None else _env_budget_bytes()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.evicted_extent_bytes = 0  # cumulative; paging tests diff this
        self.stale_pin_reclaims = 0
        self.quota_evictions = 0  # subset of evictions: tenant-quota passes

    # -- core --------------------------------------------------------------

    def get(self, key: Tuple) -> Optional[object]:
        with self._mu:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return arr

    def put(
        self,
        key: Tuple,
        arr: object,
        *,
        extent: bool = False,
        shards: Optional[Iterable[int]] = None,
        index: Optional[str] = None,
    ) -> None:
        nb = _nbytes(arr)
        with self._mu:
            self._put_locked(
                key, arr, nb, extent=extent, shards=shards, index=index
            )

    def _put_locked(
        self,
        key: Tuple,
        arr: object,
        nb: int,
        *,
        extent: bool,
        shards: Optional[Iterable[int]] = None,
        index: Optional[str] = None,
    ) -> None:
        if key in self._entries:
            # replace: the old bytes leave the ledger even if pinned (the
            # pins transfer to the new array — stage-level code only pins
            # entries it just fetched/built, so a same-key replace means
            # the pin holder is being handed the new array anyway)
            self._drop_locked(key, replacing=True)
        self._entries[key] = arr
        self._sizes[key] = nb
        self._by_owner.setdefault(key[0], set()).add(key)
        if extent:
            self._extent_keys.add(key)
        if shards is not None:
            self._cover[key] = frozenset(shards)
        if index is not None:
            self._key_index[key] = index
        self._bytes += nb
        self._evict_locked(keep=key)

    def get_or_build(
        self,
        key: Tuple,
        build: Callable[[], object],
        *,
        extent: bool = False,
        pin: bool = False,
        shards: Optional[Iterable[int]] = None,
        index: Optional[str] = None,
    ) -> object:
        """Return the cached array for `key`, building it at most once
        process-wide even under concurrent callers (single-flight). With
        pin=True the returned entry is pinned under the same lock hold
        that found/inserted it — no eviction window in between."""
        with self._mu:
            while True:
                arr = self._entries.get(key)
                if arr is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    if pin:
                        self._pin_locked(key)
                    return arr
                if key not in self._building:
                    self._building.add(key)
                    self.misses += 1
                    break
                # a peer is building this key: wait for its insert instead
                # of double-building (and double-charging the byte ledger)
                self._build_cv.wait()
        import time as _t

        t_build0 = _t.perf_counter()
        try:
            arr = build()
        except BaseException:
            with self._mu:
                self._building.discard(key)
                self._build_cv.notify_all()
            raise
        nb = _nbytes(arr)
        if not extent:
            # flight-recorder staging attribution for NON-extent entries
            # (TopN tally bundles etc.) — extent staging is accounted by
            # hbm/residency, which wraps the whole assembly
            from pilosa_tpu.utils import tracing as _tracing

            _tracing.note_stage(
                nbytes=nb, seconds=_t.perf_counter() - t_build0
            )
        with self._mu:
            self._building.discard(key)
            self._put_locked(
                key, arr, nb, extent=extent, shards=shards, index=index
            )
            if pin:
                self._pin_locked(key)
            self._build_cv.notify_all()
        return arr

    def invalidate(self, key: Tuple) -> None:
        with self._mu:
            if key in self._entries:
                self._drop_locked(key)

    def invalidate_many(self, keys: Iterable[Tuple]) -> None:
        """Drop a batch of keys under ONE lock hold (bulk ingest
        reconciles a whole batch's touched rows in one pass instead of
        one lock acquisition per row)."""
        with self._mu:
            for key in keys:
                if key in self._entries:
                    self._drop_locked(key)

    def invalidate_owner(self, owner: Hashable) -> None:
        with self._mu:
            for key in list(self._by_owner.get(owner, ())):
                self._drop_locked(key)

    def invalidate_owners(self, owners: Iterable[Hashable]) -> None:
        """invalidate_owner for a batch of owner tokens under one lock
        hold (the ingest fast path drops many fragments' row entries per
        import call)."""
        with self._mu:
            for owner in owners:
                for key in list(self._by_owner.get(owner, ())):
                    self._drop_locked(key)

    def invalidate_owner_shard(self, owner: Hashable, shard: int) -> None:
        """Dirty-extent invalidation: drop only this owner's entries
        whose registered shard coverage contains `shard` (entries without
        coverage are dropped conservatively). A single-shard write then
        frees just the covering extent(s), not the owner's whole stack
        set — the read side re-stages only those slices."""
        with self._mu:
            for key in list(self._by_owner.get(owner, ())):
                cov = self._cover.get(key)
                if cov is None or shard in cov:
                    self._drop_locked(key)

    def invalidate_owner_uncovered(self, owner: Hashable) -> None:
        """Drop this owner's entries with NO registered shard coverage
        (ad-hoc builds like the TopN tally bundles, which are not
        version-keyed). The staged write path invalidates these eagerly
        while coverage-registered extents — version-keyed, hence never
        served stale — defer to the merge barrier's patch-or-invalidate
        reconciliation (core/view.py sync_pending)."""
        with self._mu:
            for key in list(self._by_owner.get(owner, ())):
                if self._cover.get(key) is None:
                    self._drop_locked(key)

    def owner_entries(
        self, owner: Hashable
    ) -> List[Tuple[Tuple, Optional[frozenset], bool]]:
        """Snapshot of one owner's live entries as
        [(key, coverage_or_None, is_extent)] under one lock hold — the
        merge barrier's extent reconciliation walks this to decide
        patch vs invalidate per entry."""
        with self._mu:
            return [
                (k, self._cover.get(k), k in self._extent_keys)
                for k in self._by_owner.get(owner, ())
            ]

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._sizes.clear()
            self._by_owner.clear()
            self._extent_keys.clear()
            self._cover.clear()
            self._key_index.clear()
            for key, n in self._pins.items():
                for _ in range(n):
                    resources.release("hbm.pin", key)
            self._pins.clear()
            self._pin_t0.clear()
            self._zombies.clear()
            self._bytes = 0

    @contextmanager
    def deferred_eviction(self) -> Iterator[None]:
        """Suspend budget eviction for the duration (nestable; settles —
        evicts down to budget — when the outermost session exits). Used
        by the stacked lowering around operand staging; see _defer_evict."""
        with self._mu:
            self._defer_evict += 1
        try:
            yield
        finally:
            with self._mu:
                self._defer_evict -= 1
                if self._defer_evict == 0:
                    self._evict_locked(keep=None)

    # -- pinning -----------------------------------------------------------

    def pin_if_present(self, key: Tuple) -> bool:
        """Pin `key` iff it is resident; True when the pin was taken."""
        with self._mu:
            if key not in self._entries:
                return False
            self._entries.move_to_end(key)
            self._pin_locked(key)
            return True

    def _pin_locked(self, key: Tuple) -> None:
        n = self._pins.get(key, 0)
        self._pins[key] = n + 1
        if n == 0:
            self._pin_t0[key] = self._clock()
        resources.acquire("hbm.pin", key)

    def unpin(self, key: Tuple) -> None:
        """Release one pin. Unpinning an unknown key is a no-op (the pin
        may have been force-released by the stale-pin safety valve)."""
        with self._mu:
            n = self._pins.get(key, 0)
            if n >= 1:
                resources.release("hbm.pin", key)
            if n <= 1:
                self._pins.pop(key, None)
                self._pin_t0.pop(key, None)
                zb = self._zombies.pop(key, None)
                if zb is not None:
                    # last pin on an invalidated entry: the in-flight
                    # operand is done with it — bytes leave the ledger now
                    self._bytes -= zb
                    if key not in self._entries:
                        self._key_index.pop(key, None)
                if n == 1:
                    # unpinned entries become evictable: settle any debt
                    # deferred while the dispatch was in flight
                    self._evict_locked(keep=None)
            else:
                self._pins[key] = n - 1

    def unpin_all(self, keys: Iterable[Tuple]) -> None:
        for key in keys:
            self.unpin(key)

    def _pinned_locked(self, key: Tuple) -> bool:
        if key not in self._pins:
            return False
        if (
            self.pin_timeout > 0
            and self._clock() - self._pin_t0.get(key, 0.0) > self.pin_timeout
        ):
            # leak safety valve: a pin this old is a bug, not a dispatch;
            # force-release it so the budget cannot wedge permanently
            for _ in range(self._pins.pop(key, 0)):
                resources.release("hbm.pin", key)
            self._pin_t0.pop(key, None)
            self.stale_pin_reclaims += 1
            return False
        return True

    @property
    def pinned_bytes(self) -> int:
        with self._mu:
            return self._pinned_bytes_locked()

    def _pinned_bytes_locked(self) -> int:
        total = 0
        for key in self._pins:
            total += self._sizes.get(key) or self._zombies.get(key, 0)
        return total

    # -- internals ---------------------------------------------------------

    def _drop_locked(self, key: Tuple, replacing: bool = False) -> None:
        self._entries.pop(key, None)
        nb = self._sizes.pop(key, 0)
        if not replacing and key in self._pins:
            # invalidated while an in-flight dispatch holds it: the array
            # lives until the last unpin, so its bytes stay accounted —
            # and stay ATTRIBUTED (the index tag is released with the
            # zombie bytes, not here, so per-index sums keep reconciling
            # with the ledger while the operand is in flight)
            self._zombies[key] = self._zombies.get(key, 0) + nb
        else:
            self._bytes -= nb
            if key not in self._zombies:
                self._key_index.pop(key, None)
        self._extent_keys.discard(key)
        self._cover.pop(key, None)
        owner_keys = self._by_owner.get(key[0])
        if owner_keys is not None:
            owner_keys.discard(key)
            if not owner_keys:
                del self._by_owner[key[0]]

    def _evict_locked(self, keep: Optional[Tuple]) -> None:
        if self._defer_evict > 0:
            return
        if self._index_quota or self._index_quota_default > 0:
            # tenant quotas first: pressure lands on over-quota owners
            # before any in-quota entry is touched, and an index is held
            # to its own quota even with global budget to spare
            self._evict_over_quota_locked(keep)
        if self._bytes <= self.budget_bytes:
            return
        for key in list(self._entries):
            if self._bytes <= self.budget_bytes or len(self._entries) <= 1:
                break
            if key == keep:
                # the just-inserted entry is the only way to finish the
                # current query; evict around it
                continue
            if self._pinned_locked(key):
                # pinned by an in-flight dispatch: eviction is DEFERRED —
                # the budget may be transiently exceeded; unpin() retries
                continue
            if key in self._extent_keys:
                self.evicted_extent_bytes += self._sizes.get(key, 0)
            self._drop_locked(key)
            self.evictions += 1

    def _quota_for_locked(self, index: str) -> int:
        q = self._index_quota.get(index)
        return q if q is not None else self._index_quota_default

    def _evict_over_quota_locked(self, keep: Optional[Tuple]) -> None:
        """Per-index quota pass (LRU order within each owner). Counts
        ZOMBIE bytes against the owner — invalidated-while-pinned device
        memory is genuinely held on that tenant's behalf — but can only
        evict live unpinned entries, so a tenant whose quota is consumed
        by in-flight pins overshoots transiently, exactly like the
        global budget does."""
        by_idx = self._index_bytes_locked()
        for key in list(self._entries):
            if len(self._entries) <= 1:
                break
            if key == keep:
                continue
            idx = self._key_index.get(key, "-")
            if idx == "-":
                continue  # unattributed system entries are not a tenant
            quota = self._quota_for_locked(idx)
            if quota <= 0:
                continue
            held = by_idx.get(idx, 0)
            if held <= quota:
                continue
            if self._pinned_locked(key):
                continue
            nb = self._sizes.get(key, 0)
            if nb >= held and nb > quota:
                # a single entry larger than the whole quota is still
                # admitted when it is ALL the index holds (the query
                # needs it to run) — same oversized-entry rule as the
                # global budget; it goes once the index holds more
                continue
            if key in self._extent_keys:
                self.evicted_extent_bytes += nb
            self._drop_locked(key)
            by_idx[idx] = held - nb
            self.evictions += 1
            self.quota_evictions += 1
            self._quota_evictions_index[idx] = (
                self._quota_evictions_index.get(idx, 0) + 1
            )

    # -- introspection -----------------------------------------------------

    @property
    def bytes_used(self) -> int:
        # under the ledger lock: the bare read was the race detector's
        # first true positive (a torn view during a replace/evict pass
        # could report bytes that never existed); one uncontended
        # acquire per gauge scrape is free
        with self._mu:
            return self._bytes

    def index_resident_bytes(self) -> Dict[str, int]:
        """Resident device bytes grouped by owning INDEX (the per-tenant
        attribution the telemetry plane publishes as `hbm.resident_bytes`
        with an `index:` label). Entries inserted without an index tag
        land in "-"; zombie bytes (invalidated-while-pinned) keep their
        attribution until the last unpin releases them. Invariant —
        regression-tested under eviction pressure: the sum over every
        bucket equals `bytes_used` byte-for-byte, because both are
        computed from the same _sizes/_zombies ledgers under one lock
        hold."""
        with self._mu:
            return self._index_bytes_locked()

    def _index_bytes_locked(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for key, nb in self._sizes.items():
            idx = self._key_index.get(key, "-")
            out[idx] = out.get(idx, 0) + nb
        for key, nb in self._zombies.items():
            idx = self._key_index.get(key, "-")
            out[idx] = out.get(idx, 0) + nb
        return out

    def configure_quotas(
        self,
        default_bytes: int = 0,
        overrides: Optional[Dict[str, int]] = None,
    ) -> None:
        """Install per-index residency quotas ([tenants] section; 0 =
        unlimited) and settle immediately: an index already over its new
        quota sheds its own LRU entries now, not at its next insert."""
        with self._mu:
            self._index_quota_default = max(0, int(default_bytes))
            self._index_quota = {
                k: max(0, int(v)) for k, v in (overrides or {}).items()
            }
            self._evict_locked(keep=None)

    def quota_evictions_by_index(self) -> Dict[str, int]:
        """Cumulative tenant-quota evictions per index (published as
        `tenant.quota_evictions{cache=hbm}` gauges)."""
        with self._mu:
            return dict(self._quota_evictions_index)

    def drop_index_attribution(self, index: str) -> None:
        """Label GC for a deleted index: re-bucket any surviving
        attribution — zombie bytes still held by an in-flight dispatch's
        pins — into "-". Without this, the tick after
        drop_index_telemetry would re-create the dropped per-index gauge
        series from the zombie entry and the label would live at 0
        forever. The per-index sum still equals the global ledger; the
        orphaned bytes just report as unattributed until the last unpin
        releases them."""
        with self._mu:
            for key in [
                k for k, v in self._key_index.items() if v == index
            ]:
                del self._key_index[key]
            # tenant ledger GC rides along: the per-index eviction
            # counter must not outlive the index (its gauge series was
            # just dropped). The quota OVERRIDE stays — it is operator
            # config, bounded by config size, and must re-apply if the
            # index is recreated.
            self._quota_evictions_index.pop(index, None)

    def owner_resident_bytes(self, owner: Hashable) -> int:
        """Resident bytes cached under one owner token (the admission
        cost estimator discounts queries whose operands are already on
        device, sched/cost.py)."""
        with self._mu:
            keys = self._by_owner.get(owner)
            if not keys:
                return 0
            return sum(self._sizes.get(k, 0) for k in keys)

    def __len__(self) -> int:
        return len(self._entries)

    def stats_snapshot(self) -> Dict[str, int]:
        """One consistent view of the residency counters (exported as
        gauges on /metrics and /debug/vars by NodeServer)."""
        with self._mu:
            return {
                "resident_bytes": self._bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "hits": self.hits,
                "misses": self.misses,
                "budget_bytes": self.budget_bytes,
                "resident_extents": len(self._extent_keys),
                "pinned_bytes": self._pinned_bytes_locked(),
                "evicted_extent_bytes": self.evicted_extent_bytes,
                "stale_pin_reclaims": self.stale_pin_reclaims,
                "quota_evictions": self.quota_evictions,
            }


# Process-global instance shared by fragments, views and the hbm extent
# layer. Tests may swap the budget (set_budget) or replace the instance
# outright.
DEVICE_CACHE = DeviceCache()


def set_budget(budget_bytes: int) -> None:
    DEVICE_CACHE.budget_bytes = budget_bytes


def _pin_probe() -> List[str]:
    """Conftest leak probe (utils/resources.py): every pin staging takes
    must be released by the plan's dispatch finally or an executor error
    path. A leaked pin makes its bytes permanently unevictable — the
    budget wedges a little tighter on every leak. Clears the cache on
    failure so one leak doesn't cascade into later tests."""
    snap = DEVICE_CACHE.stats_snapshot()
    if snap["pinned_bytes"]:
        DEVICE_CACHE.clear()
        return [
            f"device-cache extent pins leaked: {snap['pinned_bytes']} "
            "bytes still pinned after the test"
        ]
    return []


resources.register_probe("hbm.pin", _pin_probe)
