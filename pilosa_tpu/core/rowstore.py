"""Host-side row storage for one fragment.

The TPU-native answer to roaring's three container encodings
(reference: roaring/roaring.go:1940 ArrayMaxSize / runMaxSize thresholds,
optimize() at :2334): on the *host*, a row's in-shard bits are kept either as
a sorted uint32 position array (sparse) or a dense uint32 word vector — the
two representations auto-convert at the memory crossover point, mirroring
roaring's array<->bitmap conversion. On the *device*, everything is dense;
compression never reaches the compute path.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

ARRAY_REP = 0
DENSE_REP = 1

# Opt-in invariant checking on the hot mutation funnel — the analog of the
# reference's roaringparanoia/roaringsentinel build tags
# (roaring/roaring_paranoia.go:15). Read once at import, like a build tag.
PARANOIA = os.environ.get("PILOSA_TPU_PARANOIA", "") in ("1", "true")

if hasattr(np, "bitwise_count"):  # numpy >= 2.0

    def _popcount_words(words: np.ndarray) -> int:
        return int(np.bitwise_count(words).sum())

else:
    # 16-bit popcount lookup table (128 KiB once) — avoids the 32x blowup of
    # np.unpackbits on hot count paths.
    _POPCNT16 = np.array([bin(i).count("1") for i in range(1 << 16)], dtype=np.uint8)

    def _popcount_words(words: np.ndarray) -> int:
        return int(_POPCNT16[words.view(np.uint16)].sum())


class RowBits:
    """Bits of one (row, shard) pair: sorted uint32 positions or dense words.

    The crossover: a position array costs 4n bytes, dense costs n_words*4
    bytes, so we densify once n > n_words (the same economics as roaring's
    ArrayMaxSize=4096 for 2^16-bit containers, scaled to the full shard).
    """

    __slots__ = ("n_bits", "n_words", "positions", "dense", "_n")

    def __init__(self, n_bits: int):
        self.n_bits = n_bits
        self.n_words = n_bits // 32
        self.positions: Optional[np.ndarray] = np.empty(0, dtype=np.uint32)
        self.dense: Optional[np.ndarray] = None
        self._n = 0  # maintained cardinality while dense (O(1) count())

    # -- representation management ---------------------------------------

    def _maybe_densify(self):
        if self.positions is not None and len(self.positions) > self.n_words:
            self._n = len(self.positions)
            self.dense = self._to_dense()
            self.positions = None

    def _maybe_sparsify(self):
        # Convert back when well under the threshold (hysteresis at 1/2).
        if self.dense is not None:
            n = self.count()
            if n < self.n_words // 2:
                self.positions = self.to_positions()
                self.dense = None

    def _to_dense(self) -> np.ndarray:
        words = np.zeros(self.n_words, dtype=np.uint32)
        if len(self.positions):
            p = self.positions
            np.bitwise_or.at(words, p >> 5, np.uint32(1) << (p & np.uint32(31)))
        return words

    # -- reads -------------------------------------------------------------

    def count(self) -> int:
        """Cardinality in O(1): maintained incrementally while dense, the
        array length while sparse. Exact counts being free host metadata is
        what lets TopN answer from rank caches with no device pass (the
        reference recounts rows because its cache counts are approximate,
        cache.go:136-300)."""
        if self.dense is not None:
            return self._n
        return len(self.positions)

    def to_words(self) -> np.ndarray:
        """Dense uint32 word vector. The dense branch hands out a read-only
        view of the live buffer (not a copy): mutating it would desync the
        maintained cardinality, which TopN answers from with no recount."""
        if self.dense is not None:
            w = self.dense.view()
            w.flags.writeable = False
            return w
        return self._to_dense()

    def to_positions(self) -> np.ndarray:
        if self.dense is not None:
            bits = np.unpackbits(self.dense.view(np.uint8), bitorder="little")
            return np.nonzero(bits)[0].astype(np.uint32)
        return self.positions.copy()

    def contains(self, pos: int) -> bool:
        if self.dense is not None:
            return bool((self.dense[pos >> 5] >> np.uint32(pos & 31)) & np.uint32(1))
        i = np.searchsorted(self.positions, pos)
        return i < len(self.positions) and self.positions[i] == pos

    def any(self) -> bool:
        if self.dense is not None:
            return bool(self.dense.any())
        return len(self.positions) > 0

    # -- mutations ---------------------------------------------------------

    def add(self, new: np.ndarray) -> int:
        """Set the given positions; returns how many were newly set."""
        new = np.asarray(new, dtype=np.uint32)
        if new.size == 0:
            return 0
        if self.dense is not None:
            w = new >> 5
            m = np.uint32(1) << (new & np.uint32(31))
            before = (self.dense[w] & m) != 0
            np.bitwise_or.at(self.dense, w, m)
            # recount duplicates: a position listed twice must count once
            if before.all():
                return 0
            uniq = np.unique(new[~before])
            self._n += len(uniq)
            return len(uniq)
        merged = np.union1d(self.positions, new)
        changed = len(merged) - len(self.positions)
        self.positions = merged.astype(np.uint32)
        self._maybe_densify()
        return changed

    def union_words(self, words: np.ndarray) -> int:
        """Union a dense word vector in; returns how many bits were newly
        set. The word-level bulk path (the reference unions whole serialized
        bitmaps in place the same way, roaring.go:1511 ImportRoaringBits)."""
        words = np.asarray(words, dtype=np.uint32)
        if not words.any():
            return 0
        before = self.count()
        if self.dense is None:
            self.dense = self._to_dense()
            self.positions = None
        np.bitwise_or(self.dense, words, out=self.dense)
        self._n = _popcount_words(self.dense)
        added = self._n - before
        self._maybe_sparsify()
        return added

    def discard(self, gone: np.ndarray) -> int:
        """Clear the given positions; returns how many were actually cleared."""
        gone = np.asarray(gone, dtype=np.uint32)
        if gone.size == 0:
            return 0
        if self.dense is not None:
            gone = np.unique(gone)
            w = gone >> 5
            m = np.uint32(1) << (gone & np.uint32(31))
            before = (self.dense[w] & m) != 0
            np.bitwise_and.at(self.dense, w, np.bitwise_not(m))
            cleared = int(before.sum())
            self._n -= cleared
            self._maybe_sparsify()
            return cleared
        kept = np.setdiff1d(self.positions, gone)
        changed = len(self.positions) - len(kept)
        self.positions = kept.astype(np.uint32)
        return changed

    def first_positions(self, k: int) -> np.ndarray:
        """Up to k set positions in ascending order, without materializing
        the whole row (paranoia spot checks): sparse slices directly; dense
        unpacks only the first <=k nonzero words."""
        if self.dense is None:
            return self.positions[:k].copy()
        w_idx = np.nonzero(self.dense)[0][:k]  # each word holds >=1 bit
        if not len(w_idx):
            return np.empty(0, np.uint32)
        by = self.dense[w_idx].astype("<u4").view(np.uint8).reshape(len(w_idx), 4)
        bits = np.unpackbits(by, axis=1, bitorder="little")
        wi, bi = np.nonzero(bits)
        return (
            w_idx[wi].astype(np.uint32) * np.uint32(32) + bi.astype(np.uint32)
        )[:k]

    # -- invariants (PILOSA_TPU_PARANOIA=1) --------------------------------

    def check(self) -> None:
        """Structural invariants (reference: Bitmap.Check/Container.check,
        roaring/roaring.go:1664,3010): exactly one live representation,
        positions strictly increasing and in-range, maintained cardinality
        exact. Raises AssertionError on violation."""
        if self.dense is not None:
            if self.positions is not None:
                raise AssertionError("both dense and positions live")
            if self.dense.shape != (self.n_words,):
                raise AssertionError(
                    f"dense shape {self.dense.shape} != ({self.n_words},)"
                )
            actual = _popcount_words(self.dense)
            if actual != self._n:
                raise AssertionError(
                    f"maintained count {self._n} != actual {actual}"
                )
        else:
            p = self.positions
            if p is None:
                raise AssertionError("neither representation live")
            if len(p):
                if not np.all(np.diff(p.astype(np.int64)) > 0):
                    raise AssertionError("positions not strictly increasing")
                if int(p[-1]) >= self.n_bits:
                    raise AssertionError(
                        f"position {int(p[-1])} >= n_bits {self.n_bits}"
                    )

    # -- serialization (snapshot payload) ----------------------------------

    def rep(self) -> int:
        return DENSE_REP if self.dense is not None else ARRAY_REP

    def payload(self) -> np.ndarray:
        return self.dense if self.dense is not None else self.positions

    @classmethod
    def from_payload(cls, n_bits: int, rep: int, payload: np.ndarray) -> "RowBits":
        rb = cls(n_bits)
        if rep == DENSE_REP:
            rb.dense = payload.astype(np.uint32, copy=True)
            rb.positions = None
            rb._n = _popcount_words(rb.dense)
        else:
            rb.positions = payload.astype(np.uint32, copy=True)
        return rb
