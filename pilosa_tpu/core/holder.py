"""Holder: node-level root of the storage tree.

Reference: /root/reference/holder.go — indexes map, open/close lifecycle
(holder.go:50,137). The anti-entropy syncer/cleaner equivalents live in the
cluster layer."""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional

from pilosa_tpu.utils.locks import TrackedRLock
from pilosa_tpu.core.index import Index


class Holder:
    def __init__(self, path: Optional[str] = None):
        self.path = path  # data directory; None => in-memory
        self._mu = TrackedRLock("holder.mu")
        self._indexes: Dict[str, Index] = {}
        # (index, shard, node_id) writes that a replica missed (it was
        # down / partitioned when the write fanned out): anti-entropy is
        # what repairs them, and this set is what makes that debt VISIBLE
        # (/status pendingRepairs) instead of silent drift
        self._pending_repairs: set = set()

    def open(self) -> "Holder":
        if self.path is not None:
            os.makedirs(self.path, exist_ok=True)
            for name in sorted(os.listdir(self.path)):
                idx_dir = os.path.join(self.path, name)
                if os.path.isdir(idx_dir) and os.path.exists(
                    os.path.join(idx_dir, ".meta.json")
                ):
                    self._indexes[name] = Index(idx_dir, name).open()
        return self

    def close(self) -> None:
        with self._mu:
            for idx in self._indexes.values():
                idx.close()
            self._indexes.clear()

    def _index_path(self, name: str) -> Optional[str]:
        return None if self.path is None else os.path.join(self.path, name)

    def create_index(
        self, name: str, *, keys: bool = False, track_existence: bool = True
    ) -> Index:
        with self._mu:
            if name in self._indexes:
                raise ValueError(f"index already exists: {name}")
            idx = Index(
                self._index_path(name),
                name,
                keys=keys,
                track_existence=track_existence,
            ).open()
            self._indexes[name] = idx
            return idx

    def create_index_if_not_exists(self, name: str, **kw) -> Index:
        with self._mu:
            if name in self._indexes:
                return self._indexes[name]
            return self.create_index(name, **kw)

    def index(self, name: str) -> Optional[Index]:
        return self._indexes.get(name)

    def indexes(self) -> List[Index]:
        with self._mu:
            return [self._indexes[n] for n in sorted(self._indexes)]

    def delete_index(self, name: str) -> None:
        with self._mu:
            idx = self._indexes.pop(name, None)
            if idx is None:
                raise KeyError(f"index not found: {name}")
            idx.close()
            if idx.path is not None:
                shutil.rmtree(idx.path, ignore_errors=True)
            self.resolve_pending_repairs(index=name)

    # -- pending replica repairs -------------------------------------------

    def record_pending_repair(self, index: str, shard: int, node_id: str) -> None:
        """A write to (index, shard) was dropped on its way to replica
        `node_id`; anti-entropy owes it a repair."""
        with self._mu:
            self._pending_repairs.add((index, int(shard), node_id))

    def pending_repairs(self) -> List[tuple]:
        with self._mu:
            return sorted(self._pending_repairs)

    def pending_repair_count(self) -> int:
        with self._mu:
            return len(self._pending_repairs)

    def discard_pending_repair(self, index: str, shard: int, node_id: str) -> bool:
        """Drop ONE entry — used when anti-entropy confirms this specific
        replica was reconciled (an unreachable replica's entry must stay)."""
        with self._mu:
            try:
                self._pending_repairs.remove((index, int(shard), node_id))
                return True
            except KeyError:
                return False

    def resolve_pending_repairs(
        self, index: Optional[str] = None, shard: Optional[int] = None
    ) -> int:
        """Discard entries matching (index, shard); None matches all.
        Called when an anti-entropy pass reconciles a fragment (and when
        an index is deleted). Returns how many entries were resolved."""
        with self._mu:
            before = len(self._pending_repairs)
            self._pending_repairs = {
                (i, s, n)
                for (i, s, n) in self._pending_repairs
                if (index is not None and i != index)
                or (shard is not None and s != shard)
            }
            return before - len(self._pending_repairs)

    def fragments(self):
        """Every open fragment (indexes -> fields -> views -> fragments)."""
        for idx in self.indexes():
            for f in idx.fields(include_hidden=True):
                for v in list(f.views.values()):
                    for frag in list(v.fragments.values()):
                        yield frag

    def staged_position_count(self) -> int:
        """WAL-staged write positions not yet materialized into row
        stores: raw pending deltas plus barrier-merged layers still
        parked for the next host read (the bulk-ingest fast path defers
        merges to read barriers; the cross-fragment barrier defers the
        row-store rewrite further, to host reads). A large, growing
        value means ingest has outrun materialization — /cluster/health
        surfaces it as staging debt (the WAL still covers every bit)."""
        return sum(
            frag._pending_n + frag._premerged_n for frag in self.fragments()
        )

    def flush_caches(self) -> None:
        """Persist every fragment's rank cache (reference: holder.go:506
        monitorCacheFlush ticker)."""
        for frag in self.fragments():
            frag.flush_cache()

    def recalculate_caches(self) -> None:
        """Rebuild every fragment's rank cache from exact row counts
        (reference: api.go RecalculateCaches / recalculate-caches message)."""
        from pilosa_tpu.core.resultcache import RESULT_CACHE

        for frag in self.fragments():
            frag.recalculate_cache()
        # a rank-cache rebuild can reorder TopN with NO fragment-version
        # change, so version-keyed cached results are not protected by
        # revalidation here — drop every index's entries explicitly
        for idx in self.indexes():
            RESULT_CACHE.drop_scope(idx._cache_scope)

    def schema(self) -> List[dict]:
        """Schema description (reference: holder Schema / http /schema)."""
        out = []
        for idx in self.indexes():
            fields = []
            for f in idx.fields():
                o = f.options
                fields.append(
                    {
                        "name": f.name,
                        "options": {
                            "type": o.type,
                            "cacheType": o.cache_type,
                            "cacheSize": o.cache_size,
                            "min": o.min,
                            "max": o.max,
                            "base": o.base,
                            "bitDepth": o.bit_depth,
                            "timeQuantum": o.time_quantum,
                            "keys": o.keys,
                            "noStandardView": o.no_standard_view,
                        },
                    }
                )
            out.append(
                {
                    "name": idx.name,
                    "options": {
                        "keys": idx.keys,
                        "trackExistence": idx.track_existence,
                    },
                    "fields": fields,
                }
            )
        return out
