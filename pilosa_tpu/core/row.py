"""Row: cross-shard query-result bitmap.

Reference: /root/reference/row.go — a Row is a list of per-shard rowSegments
wrapping roaring bitmaps (row.go:27,332). Here a Row maps shard -> dense
device words; algebra is elementwise device ops per aligned shard, and counts
reduce exactly on the host (Python ints).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pilosa_tpu.ops import bitmap as ob
from pilosa_tpu.shardwidth import SHARD_WIDTH


class Row:
    __slots__ = ("segments", "attrs", "keys")

    def __init__(self, segments: Optional[Dict[int, object]] = None):
        # shard -> uint32 words (jax device array or numpy)
        self.segments: Dict[int, object] = dict(segments or {})
        self.attrs: Optional[dict] = None
        self.keys: Optional[List[str]] = None

    # -- algebra (row.go:91-330) ------------------------------------------

    def union(self, *others: "Row") -> "Row":
        out = dict(self.segments)
        for o in others:
            for shard, words in o.segments.items():
                cur = out.get(shard)
                out[shard] = words if cur is None else ob.b_or(cur, words)
        return Row(out)

    def intersect(self, *others: "Row") -> "Row":
        out = dict(self.segments)
        for o in others:
            nxt = {}
            for shard, words in o.segments.items():
                cur = out.get(shard)
                if cur is not None:
                    nxt[shard] = ob.b_and(cur, words)
            out = nxt
        return Row(out)

    def difference(self, *others: "Row") -> "Row":
        out = dict(self.segments)
        for o in others:
            for shard, words in o.segments.items():
                cur = out.get(shard)
                if cur is not None:
                    out[shard] = ob.b_andnot(cur, words)
        return Row(out)

    def xor(self, *others: "Row") -> "Row":
        out = dict(self.segments)
        for o in others:
            for shard, words in o.segments.items():
                cur = out.get(shard)
                out[shard] = words if cur is None else ob.b_xor(cur, words)
        return Row(out)

    def shift(self, n: int = 1) -> "Row":
        """Shift all columns up by n; bits crossing a shard boundary carry
        into the next shard (the reference's per-segment shift drops them —
        row.go Shift; we keep the carry, a deliberate correction)."""
        out: Dict[int, object] = {}
        carry_by_shard: Dict[int, object] = {}
        for shard in sorted(self.segments):
            shifted, overflow = ob.shift_bits(self.segments[shard], n)
            out[shard] = shifted
            if bool(ob.any_set(overflow)):
                carry_by_shard[shard + 1] = overflow
        for shard, words in carry_by_shard.items():
            cur = out.get(shard)
            out[shard] = words if cur is None else ob.b_or(cur, words)
        return Row(out)

    # -- reads -------------------------------------------------------------

    def count(self) -> int:
        return int(sum(int(ob.popcount(w)) for w in self.segments.values()))

    def any(self) -> bool:
        return any(bool(ob.any_set(w)) for w in self.segments.values())

    def columns(self) -> np.ndarray:
        """Sorted absolute column ids (host; result materialization only)."""
        cols = []
        for shard in sorted(self.segments):
            pos = ob.unpack_positions(np.asarray(self.segments[shard]))
            if len(pos):
                cols.append(pos + np.uint64(shard) * np.uint64(SHARD_WIDTH))
        return np.concatenate(cols) if cols else np.empty(0, np.uint64)

    def shards(self) -> List[int]:
        return sorted(self.segments)

    def segment(self, shard: int):
        return self.segments.get(shard)

    def includes(self, col: int) -> bool:
        words = self.segments.get(col // SHARD_WIDTH)
        if words is None:
            return False
        w = np.asarray(words)
        pos = col % SHARD_WIDTH
        return bool((int(w[pos >> 5]) >> (pos & 31)) & 1)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Row):
            return NotImplemented
        return self.columns().tolist() == other.columns().tolist()

    def __repr__(self) -> str:
        return f"Row(shards={self.shards()}, count={self.count()})"
