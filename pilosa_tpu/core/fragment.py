"""Fragment: one (index, field, view, shard) slab of bits.

Reference: /root/reference/fragment.go — the unit of storage, locking,
snapshotting and placement ("Fragment=intersection of field & shard",
NOTES:25). This rebuild keeps the same unit but splits responsibilities
TPU-style:

- host side: sparse-or-dense RowBits per row (core/rowstore.py), WAL +
  snapshot persistence (core/wal.py), mutex vector for mutex fields
  (fragment.go:670), op counting with MaxOpN snapshot triggering
  (fragment.go:84,2296).
- device side: per-row dense uint32 blocks cached in HBM; all query math
  (row algebra, BSI ladders, counts) happens there via ops/bitmap.py and
  ops/bsi.py. Host bitmap math never serves a query — the host store is the
  mutable/durable representation only.

Position convention matches fragment.go:3090:
    pos = row_id * SHARD_WIDTH + (col % SHARD_WIDTH).
"""

from __future__ import annotations

import contextlib
import os
import time
from itertools import repeat
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from pilosa_tpu.utils import resources
from pilosa_tpu.utils.locks import TrackedRLock
from pilosa_tpu.utils.race import race_checked
from pilosa_tpu.core import cache as cachemod
from pilosa_tpu.core import wal as walmod
from pilosa_tpu.core.devcache import DEVICE_CACHE, new_owner_token
from pilosa_tpu.core import merge as merge_mod
from pilosa_tpu.core import rowstore as rowstore_mod
from pilosa_tpu.core.rowstore import RowBits
from pilosa_tpu.utils.arrays import group_slices
from pilosa_tpu.ops import bitmap as ob
from pilosa_tpu.ops import bsi as obsi
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXPONENT

# Reference: fragment.go:84 — ops between snapshots.
DEFAULT_MAX_OP_N = 10_000

# BSI plane rows (reference: fragment.go:88-96).
BSI_EXISTS_BIT = 0
BSI_SIGN_BIT = 1
BSI_OFFSET_BIT = 2

# Live-transfer write capture (streaming resize): a capture that grows past
# this many positions is dropped and marked LOST — the destination refetches
# the full snapshot instead of this node buffering an unbounded delta for a
# transfer whose driver may have died.
CAPTURE_MAX_POSITIONS = 1 << 22  # ~32 MB of uint64 positions


class TransferCaptureLost(Exception):
    """The write capture backing an in-flight fragment transfer is gone
    (overflowed, replaced wholesale, or never started): the destination
    must restart from a fresh full snapshot (HTTP 410 on the delta
    endpoint), not treat the delta stream as complete."""


class TransferCutover(Exception):
    """This fragment is inside its resize-cutover write barrier: the
    coordinator quiesced it so the final capture drain is provably
    complete before the topology install. Writes are rejected with a
    retryable error (HTTP 503 + Retry-After) for the barrier's bounded
    window — the internode retry plane re-maps and lands them on the
    post-cutover owner."""


# Lazy host snapshot tier: fragments open by indexing the snapshot headers
# only, materializing RowBits from seek-reads on first access — holder
# open is O(rows), untouched rows stay on disk in the page cache (the
# host analog of the reference's zero-copy mmap storage, fragment.go:311
# + syswrap). PILOSA_TPU_LAZY_SNAPSHOTS=0 forces eager loads.
_LAZY_SNAPSHOTS = os.environ.get("PILOSA_TPU_LAZY_SNAPSHOTS", "1") in ("1", "true")


class _LazyRows:
    """MutableMapping-shaped row store over an on-disk snapshot.

    Materialized rows (mutated or read) live in `_mat` and take precedence;
    everything else is served by seeking into the snapshot file on demand
    (open-per-access: no fd is held between reads, so thousands of lazy
    fragments cost zero resident fds — the page cache keeps repeat reads
    cheap). After snapshot() rewrites the file, rebase() re-indexes against
    the new file while keeping materialized rows (they are the
    authoritative, identical state that was just written)."""

    __slots__ = ("n_bits", "path", "_mat", "_index", "_bulk_f")

    def __init__(self, path: str, expect_n_bits: int):
        _, n_bits, index = walmod.read_snapshot_index(path)
        if n_bits != expect_n_bits:
            raise ValueError(
                f"{path}: snapshot width {n_bits} != configured "
                f"SHARD_WIDTH {expect_n_bits}"
            )
        self.n_bits = n_bits
        self.path = path
        self._mat: Dict[int, RowBits] = {}
        self._index = index
        self._bulk_f = None  # shared fd during bulk() scans

    @contextlib.contextmanager
    def bulk(self):
        """Context manager holding ONE fd across a bulk scan (snapshot
        writes, cache rebuilds): per-row open/close would cost ~4 syscalls
        per row under the fragment lock."""
        if self._bulk_f is not None:  # nested: reuse
            yield
            return
        with open(self.path, "rb") as f:
            self._bulk_f = f
            try:
                yield
            finally:
                self._bulk_f = None

    def _read_payload(self, off: int, n: int) -> np.ndarray:
        f = self._bulk_f
        if f is not None:
            f.seek(off)
            data = f.read(n * 4)
        else:
            with open(self.path, "rb") as f2:
                f2.seek(off)
                data = f2.read(n * 4)
        if len(data) != n * 4:
            raise ValueError(f"{self.path}: truncated payload at {off}")
        return np.frombuffer(data, dtype="<u4")

    # -- mapping protocol --------------------------------------------------

    def __getitem__(self, row_id: int) -> RowBits:
        rb = self._mat.get(row_id)
        if rb is None:
            meta = self._index.get(row_id)
            if meta is None:
                raise KeyError(row_id)
            rep, off, n = meta
            payload = self._read_payload(off, n)
            rb = self._mat[row_id] = RowBits.from_payload(self.n_bits, rep, payload)
        return rb

    def get(self, row_id: int, default=None):
        if row_id in self._mat or row_id in self._index:
            return self[row_id]
        return default

    def __setitem__(self, row_id: int, rb: RowBits) -> None:
        self._mat[row_id] = rb

    def __delitem__(self, row_id: int) -> None:
        found = self._mat.pop(row_id, None) is not None
        found = self._index.pop(row_id, None) is not None or found
        if not found:
            raise KeyError(row_id)

    def __contains__(self, row_id) -> bool:
        return row_id in self._mat or row_id in self._index

    def __iter__(self):
        return iter(self._mat.keys() | self._index.keys())

    def __len__(self) -> int:
        return len(self._mat.keys() | self._index.keys())

    def __bool__(self) -> bool:
        return bool(self._mat) or bool(self._index)

    def items(self):
        for row_id in self:
            yield row_id, self[row_id]

    def values(self):
        for row_id in self:
            yield self[row_id]

    def keys(self):
        return self._mat.keys() | self._index.keys()

    # -- lazy-aware accessors ----------------------------------------------

    def count_of(self, row_id: int) -> int:
        """Row cardinality WITHOUT materializing: array reps know it from
        the header; dense reps popcount the mapped payload (page cache,
        no resident RowBits)."""
        rb = self._mat.get(row_id)
        if rb is not None:
            return rb.count()
        meta = self._index.get(row_id)
        if meta is None:
            return 0
        rep, off, n = meta
        if rep == rowstore_mod.ARRAY_REP:
            return n
        return rowstore_mod._popcount_words(self._read_payload(off, n))

    def rep_payload(self, row_id: int) -> Tuple[int, np.ndarray]:
        """(rep, payload) for snapshot writing, without materializing."""
        rb = self._mat.get(row_id)
        if rb is not None:
            return rb.rep(), rb.payload()
        rep, off, n = self._index[row_id]
        return rep, self._read_payload(off, n)

    def rebase(self, path: str) -> None:
        """Point unmaterialized rows at a freshly written snapshot file.
        Materialized rows may appear in both _mat and _index afterwards —
        that is fine: iteration/len use the key-set union and __getitem__
        prefers _mat, whose content is identical to what was written."""
        self.path = path
        _, _, self._index = walmod.read_snapshot_index(path)


@race_checked(exclude=(
    # version is read lock-free by design across the codebase: extent/
    # stack cache keys are version-salted, and a torn read only yields a
    # stale key that the next barrier invalidates (monotonic int, GIL-
    # atomic). on_mutate is installed once by the owning View at
    # registration, before concurrent writers exist for that view.
    "version",
    "on_mutate",
    # staged-delta counters are SNAPSHOT-read lock-free by design: the
    # merge barrier's phase-1 peek (core/merge.py merge_barrier), the
    # admission cost estimator's staged surcharge (sched/cost.py) and
    # holder.staged_position_count() all read these GIL-atomic ints
    # without the fragment lock — every consumer that ACTS on them
    # revalidates under the lock via the pending_snapshot/_pending_gen
    # handshake, so a stale peek costs one wasted pass, never a wrong
    # answer. Writes stay under _mu (LOCK004 enforces that statically).
    "_pending_n",
    "_premerged_n",
))
class Fragment:
    """One shard of one view of one field.

    Thread-safety: a single re-entrant lock guards host structures (the
    reference uses fragment.mu the same way, fragment.go:100-159).
    """

    def __init__(
        self,
        path: Optional[str],
        index: str,
        field: str,
        view: str,
        shard: int,
        *,
        mutex: bool = False,
        max_op_n: int = DEFAULT_MAX_OP_N,
        cache_type: str = cachemod.CACHE_TYPE_RANKED,
        cache_size: int = cachemod.DEFAULT_CACHE_SIZE,
    ):
        self.path = path  # None => purely in-memory (test harness)
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.mutex = mutex
        self.max_op_n = max_op_n
        # row-rank cache for TopN (reference: fragment.go:131 f.cache)
        self.cache = cachemod.make_cache(cache_type, cache_size)
        self._cache_top_arrays = None  # memoized (top, rids, cnts)
        self._cache_id_arrays = None  # memoized id-sorted (top, rids, cnts)

        self._mu = TrackedRLock("fragment.mu")
        self._rows: Dict[int, RowBits] = {}
        # Bulk-ingest fast path (stage_positions): SET positions appended
        # here are already WAL-framed and device-invalidated but not yet
        # merged into _rows; every read barrier merges them first
        # (_sync_locked) in one vectorized pass. len bookkeeping lives in
        # _pending_n so the hot check is one int compare.
        self._pending: List[np.ndarray] = []
        self._pending_n = 0
        # Cross-fragment merge handshake (core/merge.py): `_pending_gen`
        # bumps whenever pending parts are consumed (per-fragment
        # _sync_locked, batched apply_merged_delta, from_bytes reset) so
        # a barrier that snapshotted parts can tell whether a concurrent
        # path already merged them; `_staged_base_version` is the
        # mutation version just BEFORE the first un-merged staged batch
        # (each staged batch bumps version by exactly one), which is the
        # version a resident extent must be keyed at for the in-place
        # patch to be exact.
        self._pending_gen = 0
        self._staged_base_version = 0
        # Pre-merged delta layers (core/merge.py barrier outcome): each
        # is the fragment's slice of one burst's globally sorted+deduped
        # staged positions, NOT yet materialized into RowBits. The
        # barrier pays O(burst) only — the device stays exact via
        # in-place extent patches built from the same merged delta —
        # and the host row store catches up at the next HOST read:
        # every host read funnels through _sync_locked, which folds the
        # layers into the one vectorized merge pass it already runs for
        # raw pending parts (layers and pending share the row-major
        # uint64 key format). Bounded by _LAYER_CAP.
        self._premerged: List[np.ndarray] = []
        self._premerged_n = 0
        # Device residency goes through the process-global budgeted LRU
        # (core/devcache.py): per-row arrays under _token, multi-row stacks
        # under _stack_token (stacks are invalidated wholesale on mutation).
        self._token = new_owner_token()
        self._stack_token = new_owner_token()
        # Monotonic mutation counter; cross-fragment caches (view row stacks)
        # validate against it.
        self.version = 0
        self._wal: Optional[walmod.WalWriter] = None
        self._op_n = 0
        # mutex fields: col -> owning row (reference keeps a mutex vector,
        # fragment.go:670 handleMutex)
        self._mutex_map: Optional[Dict[int, int]] = {} if mutex else None
        # optional owner hook fired after any mutation (the View registers
        # one to drop its cross-shard stacks covering this fragment)
        self.on_mutate = None
        # Live-transfer write captures (streaming resize): while transfers
        # are in flight, every mutation funnel appends its records to each
        # armed capture (the same (op, positions) shape the WAL frames) so
        # destinations can replay exactly the writes that landed after
        # their snapshots. NAMED per transfer tag: at replica_n > 1 two
        # destinations stream the same source fragment concurrently, and
        # each must see the full delta — a shared buffer would let one
        # drain steal records the other never gets.
        self._captures: Dict[str, List[Tuple[int, np.ndarray]]] = {}
        self._capture_ns: Dict[str, int] = {}
        self._captures_lost: set = set()
        # resize-cutover write barrier: monotonic deadline; 0 = open. The
        # deadline (not a bool) makes the barrier self-expiring, so a lost
        # resize-release can never block a fragment's writes forever.
        self._write_block_until = 0.0
        self._open = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def snap_path(self) -> Optional[str]:
        return None if self.path is None else self.path + ".snap"

    @property
    def wal_path(self) -> Optional[str]:
        return None if self.path is None else self.path + ".wal"

    @property
    def cache_path(self) -> Optional[str]:
        return None if self.path is None else self.path + ".cache"

    def open(self) -> "Fragment":
        with self._mu:
            if self._open:
                return self
            replayed = 0
            if self.path is not None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                if os.path.exists(self.snap_path):
                    # mutex fields load eagerly: rebuilding the col->row
                    # mutex vector needs every bit anyway, so laziness
                    # would only add indexing overhead
                    if _LAZY_SNAPSHOTS and self._mutex_map is None:
                        self._rows = _LazyRows(self.snap_path, SHARD_WIDTH)
                    else:
                        _, n_bits, rows = walmod.read_snapshot(self.snap_path)
                        if n_bits != SHARD_WIDTH:
                            raise ValueError(
                                f"{self.snap_path}: snapshot width {n_bits} != "
                                f"configured SHARD_WIDTH {SHARD_WIDTH}"
                            )
                        self._rows = rows
                for op, positions in walmod.replay_wal(self.wal_path):
                    if op == walmod.OP_ROW_WORDS:
                        # commutes with staged SETs (both only set bits):
                        # no flush needed before the word union
                        self._apply_row_words(
                            int(positions[0]),
                            np.ascontiguousarray(positions[1:]).view(np.uint32),
                        )
                    elif op == walmod.OP_SET and self._mutex_map is None:
                        # replay fast path: staged OP_SET frames are
                        # already durable (they ARE the WAL), so they
                        # re-stage straight into the pending buffer and
                        # land via ONE deferred merge at the first read
                        # barrier instead of one exact apply per frame
                        if not self._pending:
                            self._staged_base_version = self.version
                        self._pending.append(
                            positions.astype(np.uint64, copy=False)
                        )
                        self._pending_n += len(positions)
                        self.version += 1
                    else:
                        # clears do not commute with staged sets: merge
                        # the pending prefix first so replay order holds
                        self._sync_locked()
                        self._apply_positions(
                            positions if op == walmod.OP_SET else np.empty(0, np.uint64),
                            positions if op == walmod.OP_CLEAR else np.empty(0, np.uint64),
                        )
                    self._op_n += len(positions)
                    replayed += 1
                if self._pending:
                    # land the whole staged replay suffix as ONE deferred
                    # merge (the fast path's contract: N staged frames,
                    # one vectorized pass) so open() returns a fully
                    # merged fragment — the rank-cache rebuild below
                    # reads _rows directly
                    self._sync_locked()
                self._wal = walmod.WalWriter(self.wal_path)
            if self._mutex_map is not None:
                self._rebuild_mutex_map()
            if self.cache.cache_type != cachemod.CACHE_TYPE_NONE:
                # The .cache sidecar is only trusted when no WAL ops were
                # replayed: snapshot() and close() flush it, so replayed
                # records mean mutations landed after the last flush and
                # the sidecar is stale. Counts are exact host metadata, so
                # the rebuild is always available.
                loaded = (
                    replayed == 0
                    and self.cache_path is not None
                    and cachemod.read_cache(self.cache_path, self.cache)
                )
                if not loaded and self._rows:
                    self.recalculate_cache()
            self._open = True
            return self

    def close(self) -> None:
        with self._mu:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
            self.flush_cache()
            DEVICE_CACHE.invalidate_owner(self._token)
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            self._open = False

    def flush_cache(self) -> None:
        """Persist the rank cache sidecar (reference: holder.go:506
        monitorCacheFlush ticker / cache.go:291 WriteTo)."""
        with self._mu:
            self._sync_locked()
            if (
                self.cache_path is not None
                and self.cache.cache_type != cachemod.CACHE_TYPE_NONE
            ):
                cachemod.write_cache(self.cache_path, self.cache)

    def recalculate_cache(self) -> None:
        """Rebuild the cache from exact per-row counts
        (reference: api.go RecalculateCaches). Lazy stores count from the
        header index / mapped payloads without materializing rows."""
        with self._mu:
            self._sync_locked()
            self.cache.clear()
            count_of = getattr(self._rows, "count_of", None)
            if count_of is not None:
                bulk = getattr(self._rows, "bulk", None)
                with bulk() if bulk is not None else contextlib.nullcontext():
                    self.cache.bulk_add(
                        (rid, count_of(rid)) for rid in self._rows
                    )
            else:
                self.cache.bulk_add(
                    (row_id, rb.count()) for row_id, rb in self._rows.items()
                )

    def _rebuild_mutex_map(self) -> None:  # guarded-by: _mu
        self._mutex_map = {}
        for row_id, rb in self._rows.items():
            for p in rb.to_positions():
                self._mutex_map[int(p)] = row_id

    # ------------------------------------------------------------------
    # reads (host metadata; bit math lives on device)
    # ------------------------------------------------------------------

    def row_ids(self) -> List[int]:
        with self._mu:
            self._sync_locked()
            return sorted(self._rows)

    def has_row(self, row_id: int) -> bool:
        with self._mu:
            self._sync_locked()
            return row_id in self._rows

    def max_row_id(self) -> Optional[int]:
        with self._mu:
            self._sync_locked()
            return max(self._rows) if self._rows else None

    def min_row_id(self) -> Optional[int]:
        with self._mu:
            self._sync_locked()
            return min(self._rows) if self._rows else None

    def row_words(self, row_id: int) -> np.ndarray:
        """Host dense words for one row (zeros if absent)."""
        with self._mu:
            self._sync_locked()
            rb = self._rows.get(row_id)
            return rb.to_words() if rb is not None else ob.empty_row()

    def row_positions(self, row_id: int) -> np.ndarray:
        with self._mu:
            self._sync_locked()
            rb = self._rows.get(row_id)
            return rb.to_positions() if rb is not None else np.empty(0, np.uint32)

    def premerge_row_words(self, row_id: int) -> np.ndarray:
        """Host words of one row at the STAGED-BASE version: the raw row
        store plus parked pre-merged layers, with pending parts excluded
        (no read barrier runs — this is NOT a host read). The merge
        barrier calls it just before parking a burst's delta layer so
        the result cache's count repair has `old_words` for
        count(new) = count(old) + popcount(delta & ~old_words), which is
        only exact against content at the burst's base version."""
        with self._mu:
            rb = self._rows.get(row_id)
            words = np.array(
                rb.to_words() if rb is not None else ob.empty_row(),
                dtype=np.uint32,
                copy=True,
            )
            lo = np.uint64(row_id) * np.uint64(SHARD_WIDTH)
            for layer in self._premerged:
                s, e = np.searchsorted(
                    layer, (lo, lo + np.uint64(SHARD_WIDTH))
                )
                if e > s:
                    cols = (layer[s:e] - lo).astype(np.uint32)
                    np.bitwise_or.at(
                        words,
                        cols >> np.uint32(5),
                        np.left_shift(np.uint32(1), cols & np.uint32(31)),
                    )
            return words

    def rows_sparse_concat(self, row_ids) -> Tuple[np.ndarray, np.ndarray]:
        """One-lock bulk sparse read for the TopN tally: concatenated
        sorted bit positions of the listed rows plus per-row lengths;
        length -1 marks a dense-rep row (the caller routes those through
        the plane path instead of gathering individual words)."""
        with self._mu:
            self._sync_locked()
            rows = self._rows
            parts = []
            lens = np.empty(len(row_ids), np.int64)
            for i, rid in enumerate(row_ids):
                rb = rows.get(rid)
                if rb is None:
                    lens[i] = 0
                elif rb.dense is not None:
                    lens[i] = -1
                else:
                    p = rb.positions
                    lens[i] = len(p)
                    if len(p):
                        parts.append(p)
            cat = np.concatenate(parts) if parts else np.empty(0, np.uint32)
            return cat, lens

    def row_device(self, row_id: int) -> jax.Array:
        """Device-resident dense row; cached (budgeted LRU) until the row
        mutates."""
        with self._mu:
            return DEVICE_CACHE.get_or_build(
                (self._token, row_id),
                lambda: jax.device_put(self.row_words(row_id)),
                index=self.index,
            )

    def rows_device(self, row_ids: Iterable[int]) -> jax.Array:
        """Stacked [k, W] device matrix for the given rows; the stack is
        cached as one budgeted entry (one transfer, not k)."""
        ids = tuple(row_ids)
        with self._mu:
            return DEVICE_CACHE.get_or_build(
                (self._stack_token, ids),
                lambda: jax.device_put(
                    np.stack([self.row_words(r) for r in ids])
                    if ids
                    else np.empty((0, SHARD_WIDTH // 32), np.uint32)
                ),
                index=self.index,
            )

    def contains(self, row_id: int, col: int) -> bool:
        with self._mu:
            self._sync_locked()
            rb = self._rows.get(row_id)
            return rb is not None and rb.contains(col % SHARD_WIDTH)

    def row_count(self, row_id: int) -> int:
        """Cardinality of one row (host metadata; used by caches/imports).
        Lazy stores answer from header metadata without materializing."""
        with self._mu:
            self._sync_locked()
            count_of = getattr(self._rows, "count_of", None)
            if count_of is not None:
                return count_of(row_id)
            rb = self._rows.get(row_id)
            return rb.count() if rb is not None else 0

    def cache_top(self):
        """Rank-cache snapshot taken under the fragment lock, so a concurrent
        writer mutating the cache in _apply_positions can't tear the read."""
        with self._mu:
            self._sync_locked()
            return self.cache.top()

    def cache_top_arrays(self):
        """(row_ids uint64[], counts uint64[]) of the rank cache in rank
        order, memoized against the cache's own top() snapshot — the
        vectorized TopN paths read these instead of building 10^4s of
        Python tuples per query."""
        with self._mu:
            self._sync_locked()
            t = self.cache.top()
            memo = self._cache_top_arrays
            if memo is None or memo[0] is not t:
                n = len(t)
                rids = np.fromiter((p[0] for p in t), np.uint64, n)
                cnts = np.fromiter((p[1] for p in t), np.uint64, n)
                memo = self._cache_top_arrays = (t, rids, cnts)
            return memo[1], memo[2]

    def cache_counts_exact(self, row_ids: np.ndarray) -> Optional[np.ndarray]:
        """uint64 cardinalities for row_ids straight from the rank cache,
        or None unless the cache is provably complete (never pruned for
        capacity): every write path maintains cache.add with the exact
        count and open rebuilds from exact counts, so an unpruned cache
        IS the full row->count map. Saves TopN pass-2's O(rows x shards)
        count() walk; pruned caches fall back to row_counts_host."""
        with self._mu:
            self._sync_locked()
            cache = self.cache
            t = cache.top() if hasattr(cache, "top") else []
            if getattr(cache, "pruned", True):
                return None  # checked AFTER top(): recalculate may prune
            memo = self._cache_id_arrays
            if memo is None or memo[0] is not t:
                # reuse the rank-order memo (pass 1 builds it) instead of
                # re-iterating the tuple list
                rids, cnts = self.cache_top_arrays()
                o = np.argsort(rids)
                memo = self._cache_id_arrays = (t, rids[o], cnts[o])
            _, rs, cs = memo
            ids = np.asarray(row_ids, np.uint64)
            if not len(rs):
                return np.zeros(len(ids), np.uint64)
            pos = np.searchsorted(rs, ids)
            posc = np.minimum(pos, len(rs) - 1)
            found = (pos < len(rs)) & (rs[posc] == ids)
            return np.where(found, cs[posc], 0).astype(np.uint64)

    def row_counts_host(self, row_ids) -> np.ndarray:
        """Cardinalities of the listed rows as one uint64 vector under one
        lock acquisition (TopN pass-2 reads n_shards x n_candidates counts;
        per-call locking would dominate)."""
        with self._mu:
            self._sync_locked()
            rows = self._rows
            count_of = getattr(rows, "count_of", None)
            if count_of is not None:
                return np.fromiter(
                    (count_of(r) for r in row_ids), np.uint64, len(row_ids)
                )
            return np.fromiter(
                (rb.count() if (rb := rows.get(r)) is not None else 0 for r in row_ids),
                np.uint64,
                len(row_ids),
            )

    # ------------------------------------------------------------------
    # writes — everything funnels through import_positions
    # ------------------------------------------------------------------

    def set_bit(self, row_id: int, col: int) -> bool:
        """Set one bit; col is the in-shard position OR an absolute column
        belonging to this shard. Returns True if it changed.
        (reference: fragment.go:647 setBit)"""
        pos = self._pos(row_id, col)
        if self._mutex_map is not None:
            return self._set_bit_mutex(row_id, col % SHARD_WIDTH)
        changed, _ = self.import_positions(np.array([pos], np.uint64), None)
        return changed > 0

    def clear_bit(self, row_id: int, col: int) -> bool:
        pos = self._pos(row_id, col)
        _, cleared = self.import_positions(None, np.array([pos], np.uint64))
        return cleared > 0

    def _set_bit_mutex(self, row_id: int, in_shard: int) -> bool:
        # the barrier defers import_positions' group-commit wait past the
        # `with self._mu` below: a strict-mode fsync round must never run
        # WITH the fragment lock held (it would serialize every reader
        # and writer of this fragment behind disk latency and defeat the
        # cross-caller coalescing)
        with walmod.GROUP_COMMIT.barrier():
            with self._mu:
                existing = self._mutex_map.get(in_shard)
                if existing == row_id:
                    return False
                to_clear = None
                if existing is not None:
                    to_clear = np.array(
                        [existing * SHARD_WIDTH + in_shard], np.uint64
                    )
                to_set = np.array([row_id * SHARD_WIDTH + in_shard], np.uint64)
                changed, _ = self.import_positions(to_set, to_clear)
                self._mutex_map[in_shard] = row_id
        return changed > 0

    def import_positions(
        self, to_set: Optional[np.ndarray], to_clear: Optional[np.ndarray]
    ) -> Tuple[int, int]:
        """Batched bit mutation by fragment position; the single EXACT
        write path (reference: fragment.go:2053 importPositions) — the
        pending ingest delta is merged first so the returned
        (n_set_changed, n_clear_changed) counts are exact. WAL framing is
        one append per import call: set+clear land as one write+flush
        instead of interleaving two syscall round-trips with the apply.
        Durability is a GROUP COMMIT: the fsync wait happens after the
        fragment lock is released, so concurrent importers coalesce into
        one commit round instead of serializing fsyncs behind each
        other's locks (strict mode; `wal-sync-interval` > 0 acks on the
        buffered write and defers the fsync to the background cadence)."""
        tok = None
        with self._mu:
            self._check_write_block_locked()
            self._sync_locked()
            records = []
            if to_set is not None and len(to_set):
                records.append((walmod.OP_SET, to_set))
            if to_clear is not None and len(to_clear):
                records.append((walmod.OP_CLEAR, to_clear))
            if records and self._wal is not None:
                tok = self._wal.append_many(records)
            for op, positions in records:
                self._capture_record(op, positions)
            n_set, n_clear = self._apply_positions(
                to_set if to_set is not None else np.empty(0, np.uint64),
                to_clear if to_clear is not None else np.empty(0, np.uint64),
            )
            self._op_n += n_set + n_clear
            if self._op_n > self.max_op_n:
                self.snapshot()
                tok = None  # snapshot fsynced + truncated: already durable
        if tok is not None:
            walmod.GROUP_COMMIT.wait_durable(tok)
        return n_set, n_clear

    def stage_positions(self, positions: np.ndarray, *, notify: bool = True) -> int:
        """Bulk-ingest fast path: append SET positions to the fragment's
        pending delta buffer WITHOUT merging them into the row store —
        the merge (one vectorized pass + a single rank-cache
        reconciliation, however many batches accumulated) is deferred to
        the next read barrier (_sync_locked). Durability is NOT deferred:
        the batch is WAL-framed here, so a crash before the merge replays
        it on open. Returns the number of staged positions (an upper
        bound on changed bits; exact change counts exist only at merge
        time — callers needing them use import_positions).

        notify=False skips the per-fragment device-cache invalidation and
        the on_mutate hook (the version still bumps): the field-level
        bulk router batches those into one device-cache pass for ALL
        fragments it touched, instead of two global-lock hits per shard.

        Mutex fields cannot take this path (last-write-wins needs the
        mutex vector consulted at apply time)."""
        positions = np.asarray(positions, dtype=np.uint64)
        n = len(positions)
        with self._mu:
            # mutex-ness never changes after construction, but the map
            # itself is guarded state: check under the lock (LOCK005) —
            # and BEFORE the empty-batch return, so misrouting a mutex
            # field through the staging path raises on every call, not
            # only on non-empty batches
            if self._mutex_map is not None:
                raise ValueError(
                    "stage_positions is not supported on mutex fields"
                )
            if not n:
                return 0
            self._check_write_block_locked()
            tok = self._wal_append(walmod.OP_SET, positions)
            self._capture_record(walmod.OP_SET, positions)
            if not self._pending:
                self._staged_base_version = self.version
            self._pending.append(positions)
            self._pending_n += n
            self._op_n += n
            self.version += 1
            if notify:
                DEVICE_CACHE.invalidate_owner(self._token)
                DEVICE_CACHE.invalidate_owner(self._stack_token)
                if self.on_mutate is not None:
                    self.on_mutate()
            if self._op_n > self.max_op_n:
                self.snapshot()  # merges pending first (snapshot reads rows)
                tok = None  # snapshot fsynced + truncated: already durable
        if tok is not None:
            # group-commit durability wait OUTSIDE the fragment lock:
            # View.stage_bulk wraps its whole per-shard loop in a
            # GROUP_COMMIT.barrier(), so a bulk import pays ONE commit
            # round however many fragments it staged
            walmod.GROUP_COMMIT.wait_durable(tok)
        return n

    def _sync_locked(self) -> None:
        """Merge the pending ingest delta into the row store. Called (under
        self._mu) at the top of every host read; device rebuild paths all
        funnel through row_words, so a staged-then-queried fragment is
        merged exactly once, not per row. Device invalidation and version
        bumps already happened at stage time — this only moves bits and
        reconciles the rank cache. Pre-merged barrier layers fold into
        the same single pass (they are already sorted/deduped row-major
        keys, the exact format of a raw pending part)."""
        if not self._pending_n and not self._premerged:
            return
        if self._pending:
            # parked layers were already booked at their barrier
            merge_mod.note_host_sync(len(self._pending))
        parts = self._premerged + self._pending
        self._premerged = []
        self._premerged_n = 0
        self._pending = []
        self._pending_n = 0
        self._pending_gen += 1  # a barrier's snapshot of `parts` is stale now
        inc = parts[0] if len(parts) == 1 else np.concatenate(parts)
        touched: set = set()
        self._bulk_set_sparse(inc, touched)
        rows_store = self._rows
        self.cache.add_many(
            (rid, rb.count() if (rb := rows_store.get(rid)) is not None else 0)
            for rid in touched
        )
        if rowstore_mod.PARANOIA:
            self._paranoia_check(touched)

    # -- cross-fragment merge barrier handshake (core/merge.py) --------

    def sync_pending_now(self) -> None:
        """Force the per-fragment merge (the barrier's fallback when key
        packing would overflow, and the bench's per-fragment baseline)."""
        with self._mu:
            self._sync_locked()

    def pending_snapshot(self):
        """Barrier phase 1: (parts, n_parts, gen, base_version) of the
        CURRENT pending delta, or None when there is nothing staged.
        `parts` is a copy of the list (the arrays are shared — staged
        buffers are append-only); nothing is popped, so a concurrent
        per-fragment read barrier stays exact."""
        with self._mu:
            if not self._pending:
                return None
            return (
                list(self._pending),
                len(self._pending),
                self._pending_gen,
                self._staged_base_version,
            )

    # Parked pre-merged layers above this many total keys fold into the
    # row store inline at the barrier instead of lazily at the next
    # host read: the layers pin the barriers' shared merged buffers,
    # and a fragment nobody host-reads must not accumulate them
    # without bound.
    _LAYER_CAP = 1 << 20

    def apply_merged_delta(
        self,
        keys_local: np.ndarray,
        n_parts: int,
        captured_n: int,
        gen: int,
    ) -> Optional[int]:
        """Barrier phase 2: accept the burst's merged delta —
        `keys_local` is this fragment's slice of the globally
        sorted+deduped staged positions (row-major uint64 keys, the
        same format as a raw pending part) covering exactly the first
        `n_parts` pending batches — trim those batches and PARK the
        layer. Returns the fragment's current version, or None when
        `gen` is stale (a concurrent `_sync_locked` already merged the
        captured parts, so applying again would only redo finished
        work).

        Materialization into RowBits is DEFERRED to the fragment's
        next HOST read: `_sync_locked` folds parked layers into the
        one vectorized merge pass it already runs — the contract that
        already ordered staged deltas before row reads. The device
        path needs no host rows at all (resident extents are patched
        in place with this same merged delta), so a barrier under
        sustained device-served load pays O(burst), never a row-store
        rewrite. WAL durability is untouched — the staged frames stay
        on disk until a snapshot, and a crash replays them into
        pending as before."""
        with self._mu:
            if gen != self._pending_gen:
                return None
            # crash-matrix injection point: a kill here leaves every
            # staged WAL frame on disk (merges never truncate), so
            # restart replay rebuilds the exact pre-install state
            walmod.fault_point("merge.install", self.path or "")
            del self._pending[:n_parts]
            self._pending_n -= captured_n
            self._pending_gen += 1
            self._staged_base_version += n_parts
            self._premerged.append(keys_local)
            self._premerged_n += len(keys_local)
            if self._premerged_n > self._LAYER_CAP:
                self._sync_locked()  # bound the parked-layer debt
            return self.version

    def _apply_positions(  # guarded-by: _mu (every mutation funnel holds it)
        self, to_set: np.ndarray, to_clear: np.ndarray
    ) -> Tuple[int, int]:
        # The single EXACT mutation funnel: every write path (including WAL
        # replay, clears from Store/ClearRow, bulk clear imports) flows
        # through here or through _sync_locked, so the mutex vector and the
        # rank cache are maintained here and nowhere else. Per-row Python
        # work is limited to the row-store handoff: set/clear merges are one
        # sort + group_slices pass each, the mutex vector updates at
        # C speed (dict.update over a zip), and the rank-cache/device-cache
        # reconciliation is a single deferred pass per batch instead of two
        # pokes per touched row.
        n_set = n_clear = 0
        touched: set = set()

        if len(to_set):
            if self._mutex_map is None:
                n_set += self._bulk_set_sparse(to_set, touched)
            else:
                rows = (to_set // SHARD_WIDTH).astype(np.int64)
                cols = (to_set % SHARD_WIDTH).astype(np.uint32)
                for row_id, sl in group_slices(rows):
                    row_id = int(row_id)
                    rb = self._rows.get(row_id)
                    if rb is None:
                        rb = self._rows[row_id] = RowBits(SHARD_WIDTH)
                    row_cols = cols[sl]
                    n_set += rb.add(row_cols)
                    touched.add(row_id)
                    self._mutex_map.update(
                        zip(row_cols.tolist(), repeat(row_id))
                    )
        if len(to_clear):
            n_clear += self._bulk_clear_sparse(to_clear, touched)
            if self._mutex_map is not None:
                mm = self._mutex_map
                rows = (to_clear // SHARD_WIDTH).astype(np.int64)
                cols = (to_clear % SHARD_WIDTH).astype(np.uint32)
                for row_id, sl in group_slices(rows):
                    row_id = int(row_id)
                    for c in cols[sl].tolist():
                        if mm.get(c) == row_id:
                            del mm[c]
        if touched:
            rows_store = self._rows
            self.cache.add_many(
                (
                    rid,
                    rb.count() if (rb := rows_store.get(rid)) is not None else 0,
                )
                for rid in touched
            )
            DEVICE_CACHE.invalidate_many(
                (self._token, rid) for rid in touched
            )
        if rowstore_mod.PARANOIA:
            self._paranoia_check(touched)
        if touched:
            # multi-row stacks may contain any touched row; drop them all
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            self.version += 1
            if self.on_mutate is not None:
                self.on_mutate()
        return n_set, n_clear

    def _bulk_set_sparse(self, to_set: np.ndarray, touched: set) -> int:  # guarded-by: _mu
        """Set a batch of keyed positions (row*SHARD_WIDTH + col) with ONE
        merge for all sparse-rep rows: their stored position arrays and
        the incoming batch are re-keyed into the same row-major space, so
        one np.unique over the concatenation replaces a union1d per
        (row, shard) — the per-call numpy overhead used to dominate
        scattered bulk imports ~3:1. Dense-rep rows keep the per-row word
        path (their bits are cheap to OR in place)."""
        rows_arr = to_set // SHARD_WIDTH
        uniq_rows = np.unique(rows_arr).astype(np.uint64)
        dense_rows = [
            int(r)
            for r in uniq_rows
            if (rb := self._rows.get(int(r))) is not None and rb.dense is not None
        ]
        n = 0
        if dense_rows:
            m = np.isin(rows_arr, np.array(dense_rows, np.uint64))
            cols = (to_set[m] % SHARD_WIDTH).astype(np.uint32)
            for row_id, sl in group_slices(rows_arr[m].astype(np.int64)):
                rb = self._rows[int(row_id)]
                n += rb.add(cols[sl])
                touched.add(int(row_id))
            if len(dense_rows) == len(uniq_rows):
                return n
            incoming = to_set[~m]
        else:
            incoming = to_set
        dense_set = set(dense_rows)
        sparse_rows = [int(r) for r in uniq_rows if int(r) not in dense_set]
        parts = [incoming.astype(np.uint64)]
        before = 0
        for rid in sparse_rows:
            rb = self._rows.get(rid)
            if rb is not None and len(rb.positions):
                before += len(rb.positions)
                parts.append(
                    rb.positions.astype(np.uint64) + np.uint64(rid) * np.uint64(SHARD_WIDTH)
                )
        merged = np.unique(np.concatenate(parts))
        # split the sorted row-major keyspace back into per-row arrays;
        # the %/cast runs ONCE for the whole fragment, then each row takes
        # a COPY of its slice — a shared view would pin the entire merge
        # buffer for as long as any one straggler row kept its slice
        # (rows densify/rewrite independently)
        all_pos = (merged % np.uint64(SHARD_WIDTH)).astype(np.uint32)
        edges = np.searchsorted(
            merged,
            np.array(
                [r * SHARD_WIDTH for r in sparse_rows]
                + [(sparse_rows[-1] + 1) * SHARD_WIDTH],
                np.uint64,
            ),
        )
        for i, rid in enumerate(sparse_rows):
            rb = self._rows.get(rid)
            if rb is None:
                rb = self._rows[rid] = RowBits(SHARD_WIDTH)
            rb.positions = all_pos[edges[i] : edges[i + 1]].copy()
            rb._maybe_densify()
            touched.add(rid)
        n += len(merged) - before
        return n

    def _bulk_clear_sparse(self, to_clear: np.ndarray, touched: set) -> int:  # guarded-by: _mu
        """Clear a batch of keyed positions with ONE merged membership test
        for all sparse-rep rows (the clear-side mirror of _bulk_set_sparse):
        stored position arrays and the incoming batch are re-keyed into the
        same row-major space, a single searchsorted pass marks the cleared
        keys, and each shrunken row takes a copy of its surviving slice.
        Dense-rep rows keep the per-row word path (bitwise_and.at inside
        RowBits.discard). Returns how many bits were actually cleared."""
        rows_arr = to_clear // SHARD_WIDTH
        uniq_rows = np.unique(rows_arr).astype(np.uint64)
        dense_rows: List[int] = []
        sparse_rows: List[int] = []
        for r in uniq_rows:
            rb = self._rows.get(int(r))
            if rb is None:
                continue
            (dense_rows if rb.dense is not None else sparse_rows).append(int(r))
        n = 0
        if dense_rows:
            m = np.isin(rows_arr, np.array(dense_rows, np.uint64))
            cols = (to_clear[m] % SHARD_WIDTH).astype(np.uint32)
            for row_id, sl in group_slices(rows_arr[m].astype(np.int64)):
                rb = self._rows[int(row_id)]
                n += rb.discard(cols[sl])
                touched.add(int(row_id))
        if not sparse_rows:
            return n
        inc_mask = np.isin(rows_arr, np.array(sparse_rows, np.uint64))
        inc = np.unique(to_clear[inc_mask].astype(np.uint64))
        parts = []
        for rid in sparse_rows:
            p = self._rows.get(rid).positions
            if len(p):
                parts.append(
                    p.astype(np.uint64) + np.uint64(rid) * np.uint64(SHARD_WIDTH)
                )
        if not parts:
            return n
        stored = np.concatenate(parts)
        idx = np.searchsorted(inc, stored)
        idxc = np.minimum(idx, len(inc) - 1)
        hit = (idx < len(inc)) & (inc[idxc] == stored)
        kept = stored[~hit]
        n += len(stored) - len(kept)
        all_pos = (kept % np.uint64(SHARD_WIDTH)).astype(np.uint32)
        edges = np.searchsorted(
            kept,
            np.array(
                [r * SHARD_WIDTH for r in sparse_rows]
                + [(sparse_rows[-1] + 1) * SHARD_WIDTH],
                np.uint64,
            ),
        )
        for i, rid in enumerate(sparse_rows):
            rb = self._rows.get(rid)
            sl = all_pos[edges[i] : edges[i + 1]]
            if len(sl) != rb.count():
                rb.positions = sl.copy()
            touched.add(rid)
        return n

    def import_row_words(self, row_id: int, words: np.ndarray) -> int:
        """Word-level bulk union into one row — the device-native analog of
        the reference's zero-parse roaring import (fragment.go:2255
        ImportRoaringBits unioning a shipped bitmap in place): callers ship
        the row's dense uint32[W] words and they are OR'd into the store in
        one vector op. Returns how many bits were newly set."""
        words = np.ascontiguousarray(words, dtype=np.uint32)
        if words.shape != (SHARD_WIDTH // 32,):
            raise ValueError(
                f"import_row_words: want shape ({SHARD_WIDTH // 32},), got {words.shape}"
            )
        tok = None
        with self._mu:
            # see stage_positions: the mutex vector is guarded state
            if self._mutex_map is not None:
                raise ValueError(
                    "word-level import is not supported on mutex fields"
                )
            self._check_write_block_locked()
            self._sync_locked()
            if self._wal is not None or self._captures:
                payload = np.empty(1 + words.nbytes // 8, np.uint64)
                payload[0] = row_id
                payload[1:] = words.view(np.uint64)
                if self._wal is not None:
                    tok = self._wal.append(walmod.OP_ROW_WORDS, payload)
                self._capture_record(walmod.OP_ROW_WORDS, payload)
            added = self._apply_row_words(row_id, words)
            self._op_n += added
            if self._op_n > self.max_op_n:
                self.snapshot()
                tok = None  # snapshot fsynced + truncated: already durable
        if tok is not None:
            walmod.GROUP_COMMIT.wait_durable(tok)
        return added

    def _apply_row_words(self, row_id: int, words: np.ndarray) -> int:  # guarded-by: _mu
        rb = self._rows.get(row_id)
        if rb is None:
            rb = self._rows[row_id] = RowBits(SHARD_WIDTH)
        added = rb.union_words(words)
        if added:
            self.cache.add(row_id, rb.count())
            DEVICE_CACHE.invalidate((self._token, row_id))
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            self.version += 1
            if self.on_mutate is not None:
                self.on_mutate()
        if rowstore_mod.PARANOIA:
            self._paranoia_check({row_id})
        return added

    def _paranoia_check(self, touched) -> None:  # guarded-by: _mu
        """Opt-in invariant pass after every mutation (the reference's
        roaringparanoia tag, roaring/roaring_paranoia.go:15): rowstore
        structural checks plus cache/rowstore count coherence for the
        touched rows. Called under self._mu."""
        for row_id in touched:
            rb = self._rows.get(row_id)
            if rb is None:
                continue
            rb.check()
            if self.cache.cache_type != cachemod.CACHE_TYPE_NONE:
                cached = self.cache.get(row_id)
                if cached and cached != rb.count():
                    raise AssertionError(
                        f"row {row_id}: cache count {cached} != "
                        f"rowstore count {rb.count()}"
                    )
            if self._mutex_map is not None and self._open and rb.count():
                # mutex invariant: every set bit's column maps back to
                # this row in the mutex vector (bounded spot check without
                # materializing the row). Skipped during open()'s WAL
                # replay: the vector is only rebuilt after replay, so
                # snapshot-loaded columns are not in it yet.
                for col in rb.first_positions(64):
                    if self._mutex_map.get(int(col)) != row_id:
                        raise AssertionError(
                            f"mutex vector disagrees at col {int(col)}"
                        )

    def _wal_append(self, op: int, positions: np.ndarray) -> Optional[int]:
        if self._wal is not None:
            return self._wal.append(op, positions)
        return None

    def _pos(self, row_id: int, col: int) -> int:
        if col >= SHARD_WIDTH:
            min_col = self.shard * SHARD_WIDTH
            if not min_col <= col < min_col + SHARD_WIDTH:
                raise ValueError(f"column {col} out of bounds for shard {self.shard}")
        return row_id * SHARD_WIDTH + (col % SHARD_WIDTH)

    def bulk_import(self, row_ids: np.ndarray, cols: np.ndarray, clear: bool = False) -> int:
        """Batched standard import (reference: fragment.go:1997 bulkImport /
        :2011 bulkImportStandard). cols may be absolute or in-shard."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.uint64) % SHARD_WIDTH
        positions = row_ids * SHARD_WIDTH + cols
        if self._mutex_map is not None and not clear:
            return self._bulk_import_mutex(row_ids, cols)
        if clear:
            _, n = self.import_positions(None, positions)
        else:
            n, _ = self.import_positions(positions, None)
        return n

    def _bulk_import_mutex(self, row_ids: np.ndarray, cols: np.ndarray) -> int:
        """Mutex import: last write per column wins
        (reference: fragment.go:2106 bulkImportMutex). The barrier
        defers the group-commit wait until the fragment lock below is
        released (see _set_bit_mutex)."""
        with walmod.GROUP_COMMIT.barrier(), self._mu:
            # keep last occurrence per column
            _, last_idx = np.unique(cols[::-1], return_index=True)
            idx = len(cols) - 1 - last_idx
            to_set = []
            to_clear = []
            updates = {}
            for i in idx:
                col, row = int(cols[i]), int(row_ids[i])
                existing = self._mutex_map.get(col)
                if existing == row:
                    continue
                if existing is not None:
                    to_clear.append(existing * SHARD_WIDTH + col)
                to_set.append(row * SHARD_WIDTH + col)
                updates[col] = row
            n, _ = self.import_positions(
                np.array(to_set, np.uint64) if to_set else None,
                np.array(to_clear, np.uint64) if to_clear else None,
            )
            # map update only after the bits landed: import_positions can
            # raise TransferCutover (resize write barrier) and the caller
            # retries the whole batch — a pre-updated map would make the
            # retry a no-op (existing == row) and silently drop the write
            self._mutex_map.update(updates)
            return n

    # ------------------------------------------------------------------
    # BSI (int fields) — reference: fragment.go:932-1110, ladders in ops/bsi
    # ------------------------------------------------------------------

    def set_value(self, col: int, bit_depth: int, value: int, clear: bool = False) -> bool:
        """Sign+magnitude write (reference: fragment.go:936 positionsForValue)."""
        in_shard = col % SHARD_WIDTH
        uvalue = abs(value)
        to_set: List[int] = []
        to_clear: List[int] = []
        (to_clear if clear else to_set).append(BSI_EXISTS_BIT * SHARD_WIDTH + in_shard)
        (to_clear if (value >= 0 or clear) else to_set).append(
            BSI_SIGN_BIT * SHARD_WIDTH + in_shard
        )
        for i in range(bit_depth):
            p = (BSI_OFFSET_BIT + i) * SHARD_WIDTH + in_shard
            (to_set if (uvalue >> i) & 1 and not clear else to_clear).append(p)
        n_set, n_clear = self.import_positions(
            np.array(to_set, np.uint64), np.array(to_clear, np.uint64)
        )
        return (n_set + n_clear) > 0

    def import_values(self, cols: np.ndarray, values: np.ndarray, bit_depth: int) -> None:
        """Columnar BSI import: transpose columns×values into per-plane row
        sets (reference: fragment.go:2205 importValue)."""
        cols = np.asarray(cols, dtype=np.uint64) % SHARD_WIDTH
        values = np.asarray(values, dtype=np.int64)
        # last write per column wins
        _, last_idx = np.unique(cols[::-1], return_index=True)
        idx = len(cols) - 1 - last_idx
        cols, values = cols[idx], values[idx]
        mags = np.abs(values).astype(np.uint64)
        to_set = [BSI_EXISTS_BIT * SHARD_WIDTH + cols]
        to_clear = []
        neg = values < 0
        to_set.append(BSI_SIGN_BIT * SHARD_WIDTH + cols[neg])
        to_clear.append(BSI_SIGN_BIT * SHARD_WIDTH + cols[~neg])
        for i in range(bit_depth):
            has = (mags >> np.uint64(i)) & np.uint64(1) != 0
            base = (BSI_OFFSET_BIT + i) * SHARD_WIDTH
            to_set.append(base + cols[has])
            to_clear.append(base + cols[~has])
        self.import_positions(np.concatenate(to_set), np.concatenate(to_clear))

    def value(self, col: int, bit_depth: int) -> Tuple[int, bool]:
        """Read one column's BSI value (host point-read;
        reference: fragment.go:896)."""
        with self._mu:
            in_shard = col % SHARD_WIDTH
            if not self.contains(BSI_EXISTS_BIT, in_shard):
                return 0, False
            v = 0
            for i in range(bit_depth):
                if self.contains(BSI_OFFSET_BIT + i, in_shard):
                    v |= 1 << i
            if self.contains(BSI_SIGN_BIT, in_shard):
                v = -v
            return v, True

    def _bsi_stack(self, bit_depth: int):
        planes = self.rows_device(range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + bit_depth))
        exists = self.row_device(BSI_EXISTS_BIT)
        sign = self.row_device(BSI_SIGN_BIT)
        return planes, exists, sign

    _FULL_FILTER = None

    @classmethod
    def _full_filter(cls) -> jax.Array:
        if cls._FULL_FILTER is None or cls._FULL_FILTER.shape != (SHARD_WIDTH // 32,):
            cls._FULL_FILTER = jax.device_put(
                np.full(SHARD_WIDTH // 32, 0xFFFFFFFF, dtype=np.uint32)
            )
        return cls._FULL_FILTER

    def sum(self, filter_words, bit_depth: int) -> Tuple[int, int]:
        """(sum of stored base-values, count) — device per-plane counts,
        exact host combine (reference: fragment.go:1111)."""
        planes, exists, sign = self._bsi_stack(bit_depth)
        filt = filter_words if filter_words is not None else self._full_filter()
        count, pos_counts, neg_counts = obsi.sum_counts(planes, exists, sign, filt, bit_depth)
        pos_counts = np.asarray(pos_counts)
        neg_counts = np.asarray(neg_counts)
        total = sum(
            (1 << i) * (int(pos_counts[i]) - int(neg_counts[i])) for i in range(bit_depth)
        )
        return total, int(count)

    def min(self, filter_words, bit_depth: int) -> Tuple[int, int]:
        """(min stored value, count attaining it) — reference: fragment.go:1146."""
        import jax.numpy as jnp

        planes, exists, sign = self._bsi_stack(bit_depth)
        filt = filter_words if filter_words is not None else self._full_filter()
        consider = ob.b_and(exists, filt)
        if int(ob.popcount(consider)) == 0:
            return 0, 0
        negatives = ob.b_and(consider, sign)
        if int(ob.popcount(negatives)) > 0:
            mval, final = obsi.max_unsigned(planes, negatives, bit_depth)
            return -int(mval), int(ob.popcount(final))
        mval, final = obsi.min_unsigned(planes, consider, bit_depth)
        return int(mval), int(ob.popcount(final))

    def max(self, filter_words, bit_depth: int) -> Tuple[int, int]:
        """(max stored value, count attaining it) — reference: fragment.go:1191."""
        planes, exists, sign = self._bsi_stack(bit_depth)
        filt = filter_words if filter_words is not None else self._full_filter()
        consider = ob.b_and(exists, filt)
        if int(ob.popcount(consider)) == 0:
            return 0, 0
        positives = ob.b_andnot(consider, sign)
        if int(ob.popcount(positives)) == 0:
            mval, final = obsi.min_unsigned(planes, consider, bit_depth)
            return -int(mval), int(ob.popcount(final))
        mval, final = obsi.max_unsigned(planes, positives, bit_depth)
        return int(mval), int(ob.popcount(final))

    def range_op(self, op: str, bit_depth: int, predicate: int) -> jax.Array:
        """Device words of columns whose stored value satisfies `op predicate`
        (reference: fragment.go:1273 rangeOp). op in {eq,neq,lt,lte,gt,gte}."""
        planes, exists, sign = self._bsi_stack(bit_depth)
        upred = np.uint32(abs(predicate))
        if op == "eq" or op == "neq":
            base = (
                ob.b_and(exists, sign) if predicate < 0 else ob.b_andnot(exists, sign)
            )
            eq = obsi.range_eq_unsigned(base, planes, upred, bit_depth)
            if op == "eq":
                return eq
            return ob.b_andnot(exists, eq)
        # Sign decomposition. Note: the reference folds predicate -1/0 strict
        # cases into the positive-side ladder (fragment.go:1332,1405
        # `predicate >= -1 && !allowEquality`), which mis-handles e.g.
        # `> -1` (drops 0 and 1) and `< -1` (includes 0 and -1). We use the
        # exact decomposition instead:
        #   v <  p, p <= 0: negatives with mag > |p|   (strict/eq via allow_eq)
        #   v <  p, p  > 0: positives with mag < p, plus all negatives
        #   v >  p, p >= 0: positives with mag > p
        #   v >  p, p  < 0: negatives with mag < |p|, plus all positives
        positives = ob.b_andnot(exists, sign)
        negatives = ob.b_and(exists, sign)
        if op in ("lt", "lte"):
            allow_eq = op == "lte"
            if predicate > 0 or (predicate == 0 and allow_eq):
                pos = obsi.range_lt_unsigned(positives, planes, upred, bit_depth, allow_eq)
                return ob.b_or(negatives, pos)
            if predicate == 0:  # strict < 0
                return negatives
            return obsi.range_gt_unsigned(negatives, planes, upred, bit_depth, allow_eq)
        if op in ("gt", "gte"):
            allow_eq = op == "gte"
            if predicate > 0 or (predicate == 0 and allow_eq):
                return obsi.range_gt_unsigned(positives, planes, upred, bit_depth, allow_eq)
            if predicate == 0:  # strict > 0
                return obsi.range_gt_unsigned(positives, planes, upred, bit_depth, False)
            neg = obsi.range_lt_unsigned(negatives, planes, upred, bit_depth, allow_eq)
            return ob.b_or(positives, neg)
        raise ValueError(f"invalid range op {op!r}")

    def range_between(self, bit_depth: int, pmin: int, pmax: int) -> jax.Array:
        """Columns with pmin <= value <= pmax (reference: fragment.go:1463)."""
        planes, exists, sign = self._bsi_stack(bit_depth)
        umin, umax = np.uint32(abs(pmin)), np.uint32(abs(pmax))
        positives = ob.b_andnot(exists, sign)
        negatives = ob.b_and(exists, sign)
        if pmin >= 0:
            return obsi.range_between_unsigned(positives, planes, umin, umax, bit_depth)
        if pmax < 0:
            return obsi.range_between_unsigned(negatives, planes, umax, umin, bit_depth)
        pos = obsi.range_lt_unsigned(positives, planes, umax, bit_depth, True)
        neg = obsi.range_lt_unsigned(negatives, planes, umin, bit_depth, True)
        return ob.b_or(pos, neg)

    def not_null(self) -> jax.Array:
        return self.row_device(BSI_EXISTS_BIT)

    # ------------------------------------------------------------------
    # TopN support: batched row cardinalities on device
    # ------------------------------------------------------------------

    def row_counts(
        self, row_ids: List[int], filter_words=None, chunk: int = 256
    ) -> np.ndarray:
        """Cardinality of each listed row (optionally intersected with a
        filter), computed on device in chunks (reference: fragment.go:1570
        top; rank cache comes later at the field layer)."""
        import jax.numpy as jnp

        out = np.empty(len(row_ids), dtype=np.uint64)
        for i in range(0, len(row_ids), chunk):
            ids = row_ids[i : i + chunk]
            stack = self.rows_device(ids)
            if filter_words is not None:
                counts = ob.count_and_rows(stack, filter_words)
            else:
                counts = ob.popcount_rows(stack)
            out[i : i + len(ids)] = np.asarray(counts, dtype=np.uint64)
        return out

    # ------------------------------------------------------------------
    # anti-entropy + streaming (reference: fragment.go:1762-1874 Blocks,
    # :2436-2606 WriteTo/ReadFrom)
    # ------------------------------------------------------------------

    def pairs(
        self, row_lo: Optional[int] = None, row_hi: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Bits as (row_ids, in-shard cols) arrays, row-major sorted,
        optionally restricted to rows in [row_lo, row_hi)."""
        with self._mu:
            self._sync_locked()
            rows_out = []
            cols_out = []
            for row_id in sorted(self._rows):
                if row_lo is not None and row_id < row_lo:
                    continue
                if row_hi is not None and row_id >= row_hi:
                    continue
                pos = self._rows[row_id].to_positions()
                if len(pos):
                    rows_out.append(np.full(len(pos), row_id, dtype=np.uint64))
                    cols_out.append(pos.astype(np.uint64))
            if not rows_out:
                return np.empty(0, np.uint64), np.empty(0, np.uint64)
            return np.concatenate(rows_out), np.concatenate(cols_out)

    def block_checksums(self) -> Dict[int, bytes]:
        """Per-100-row-block digests for replica sync
        (reference: fragment.go:2814-2838 blockHasher)."""
        from pilosa_tpu.core.blocks import block_checksums as _bc

        return _bc(self.pairs())

    def block_pairs(self, block_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, cols) bits within one checksum block."""
        from pilosa_tpu.core.blocks import HASH_BLOCK_SIZE

        return self.pairs(block_id * HASH_BLOCK_SIZE, (block_id + 1) * HASH_BLOCK_SIZE)

    def apply_deltas(
        self, sets: Tuple[np.ndarray, np.ndarray], clears: Tuple[np.ndarray, np.ndarray]
    ) -> Tuple[int, int]:
        """Apply (rows, cols) set/clear deltas from an anti-entropy merge."""
        sr, sc = sets
        cr, cc = clears
        to_set = (
            np.asarray(sr, np.uint64) * SHARD_WIDTH + np.asarray(sc, np.uint64)
            if len(sr)
            else None
        )
        to_clear = (
            np.asarray(cr, np.uint64) * SHARD_WIDTH + np.asarray(cc, np.uint64)
            if len(cr)
            else None
        )
        return self.import_positions(to_set, to_clear)

    def to_bytes(self) -> bytes:
        """Full-fragment serialization for resize streaming / backup
        (reference: fragment.go:2436 WriteTo — streams storage as tar)."""
        import io

        with self._mu:
            self._sync_locked()
            buf = io.BytesIO()
            walmod.write_snapshot_stream(buf, self.shard, SHARD_WIDTH, self._rows)
            return buf.getvalue()

    # -- live-transfer write capture (streaming resize) ----------------

    def begin_streaming(self, tag: str = "default") -> bytes:
        """Phase 1 of a live fragment transfer: serialize the full row
        store AND, atomically under the same lock hold, arm the `tag`
        capture for every subsequent mutation — so the snapshot plus the
        captured delta is exactly this fragment's state at any later
        drain point. The fragment keeps serving reads and accepting
        writes throughout. Captures are independent per tag (one per
        destination transfer leg); re-beginning a tag replaces that
        tag's capture only (idempotent refetch)."""
        import io

        with self._mu:
            self._sync_locked()
            buf = io.BytesIO()
            walmod.write_snapshot_stream(buf, self.shard, SHARD_WIDTH, self._rows)
            if tag not in self._captures:
                resources.acquire("fragment.capture", (id(self), tag))
            self._captures[tag] = []
            self._capture_ns[tag] = 0
            self._captures_lost.discard(tag)
            return buf.getvalue()

    def begin_capture_if_version(self, tag: str, version: int) -> bool:
        """Arm a `tag` write capture WITHOUT serializing, iff the
        fragment is still at `version` — the tier's snapshot-bootstrap
        offer path: the destination fetches the already-uploaded
        snapshot object (taken at `version`) from the object store, so
        object + capture is exact only if nothing mutated since the
        currency check. The version re-check and the arming share one
        lock hold, which is what closes that race; on False the caller
        falls back to classic peer streaming."""
        with self._mu:
            if self.version != version:
                return False
            self._sync_locked()
            if self.version != version:
                return False  # the sync itself merged a staged delta
            if tag not in self._captures:
                resources.acquire("fragment.capture", (id(self), tag))
            self._captures[tag] = []
            self._capture_ns[tag] = 0
            self._captures_lost.discard(tag)
            return True

    def drain_capture(self, tag: str = "default") -> bytes:
        """Phase 2: pop one tag's captured write records as one WAL-framed
        byte stream (the read barrier — concurrent writers to THIS
        fragment block only for the pop). The capture stays armed, so
        repeated drains stream catch-up rounds until the delta runs dry.
        Raises TransferCaptureLost when there is nothing to resume from."""
        with self._mu:
            records = self._captures.get(tag)
            if records is None:
                raise TransferCaptureLost(
                    f"{self.index}/{self.field}/{self.view}/{self.shard}: "
                    + ("write capture overflowed"
                       if tag in self._captures_lost
                       else "no active write capture")
                )
            self._captures[tag] = []
            self._capture_ns[tag] = 0
            return walmod.encode_records(records)

    def end_capture(self, tag: Optional[str] = None) -> None:
        """Stop capturing for `tag` (cutover complete, or transfer
        abandoned); None ends every capture. Once the last capture is
        gone the cutover write barrier (if any) lifts with it — no
        transfer can still depend on a frozen delta."""
        with self._mu:
            if tag is None:
                for t in self._captures:
                    resources.release("fragment.capture", (id(self), t))
                self._captures.clear()
                self._capture_ns.clear()
                self._captures_lost.clear()
            else:
                if tag in self._captures:
                    resources.release("fragment.capture", (id(self), tag))
                self._captures.pop(tag, None)
                self._capture_ns.pop(tag, None)
                self._captures_lost.discard(tag)
            if not self._captures:
                self._write_block_until = 0.0

    def block_writes(self, ttl: float) -> None:
        """Arm the cutover write barrier for `ttl` seconds: every mutation
        funnel raises TransferCutover until the barrier lifts (release,
        end of captures, or deadline expiry). Reads keep serving."""
        with self._mu:
            self._write_block_until = time.monotonic() + max(ttl, 0.0)

    def unblock_writes(self) -> None:
        with self._mu:
            self._write_block_until = 0.0

    def _check_write_block_locked(self) -> None:
        # called under self._mu at the top of every mutation funnel
        if not self._write_block_until:
            return
        if time.monotonic() >= self._write_block_until:
            self._write_block_until = 0.0  # lost release; self-heal
            return
        raise TransferCutover(
            f"{self.index}/{self.field}/{self.view}/{self.shard}: "
            "resize cutover in progress, retry"
        )

    def _capture_record(self, op: int, positions: np.ndarray) -> None:  # guarded-by: _mu
        # called under self._mu by every mutation funnel
        if not self._captures:
            return
        for tag in list(self._captures):
            self._captures[tag].append((op, positions))
            n = self._capture_ns[tag] + len(positions)
            if n > CAPTURE_MAX_POSITIONS:
                # unbounded buffering is worse than a refetch: drop this
                # tag's capture and make its next drain signal "restart
                # from a fresh snapshot"
                del self._captures[tag]
                del self._capture_ns[tag]
                self._captures_lost.add(tag)
                resources.release("fragment.capture", (id(self), tag))
            else:
                self._capture_ns[tag] = n

    def apply_transfer_records(self, data: bytes) -> int:
        """Destination-side delta replay: apply a drain_capture() byte
        stream through the normal exact write funnels (WAL-framed and
        device-invalidated like any other write). The whole stream is
        decoded BEFORE the first record applies: decode_records is strict,
        and materializing up front is what actually honors its torn-wire
        contract — a ValueError mid-iteration after a partial apply would
        leave this fragment holding an un-resumable prefix. Returns
        positions applied."""
        records = list(walmod.decode_records(data))
        n = 0
        # one group-commit round for the whole delta, not one per record
        with walmod.GROUP_COMMIT.barrier():
            for op, positions in records:
                if op == walmod.OP_ROW_WORDS:
                    words = np.ascontiguousarray(positions[1:]).view(np.uint32)
                    self.import_row_words(int(positions[0]), words)
                    # count set BITS, not payload words: `n` feeds
                    # resize.delta_positions and the job's deltas counter,
                    # documented as write positions — a whole-row union
                    # record would otherwise add 1 + words_per_row
                    # regardless of how many bits the row carries
                    n += int(np.unpackbits(words.view(np.uint8)).sum())
                else:
                    if op == walmod.OP_SET:
                        self.import_positions(positions, None)
                    else:
                        self.import_positions(None, positions)
                    n += len(positions)
        return n

    def merge_from_bytes(self, data: bytes) -> int:
        """Union a snapshot stream INTO this fragment instead of replacing
        it — the post-commit resize sweep uses this when the destination
        fragment already exists (post-cutover writes created it), where
        from_bytes' wholesale replace would erase those acknowledged
        writes. Rides import_row_words, so every merged row is WAL-framed
        and device-invalidated like any other write. Returns bits newly
        set."""
        import io

        shard, n_bits, rows = walmod.read_snapshot_stream(io.BytesIO(data))
        if shard != self.shard:
            raise ValueError(
                f"fragment stream is for shard {shard}, not {self.shard}"
            )
        if n_bits != SHARD_WIDTH:
            raise ValueError(
                f"fragment stream shard width {n_bits} != local {SHARD_WIDTH}"
            )
        added = 0
        # one group-commit round for the whole merged stream, not one
        # fsync wait per row
        with walmod.GROUP_COMMIT.barrier():
            for row_id, rb in rows.items():
                words = np.array(rb.to_words(), dtype=np.uint32)
                if words.any():
                    added += self.import_row_words(row_id, words)
        return added

    def from_bytes(self, data: bytes) -> None:
        """Replace this fragment's contents from to_bytes() output
        (reference: fragment.go:2527 ReadFrom)."""
        import io

        shard, n_bits, rows = walmod.read_snapshot_stream(io.BytesIO(data))
        if shard != self.shard:
            raise ValueError(
                f"fragment stream is for shard {shard}, not {self.shard}"
            )
        if n_bits != SHARD_WIDTH:
            raise ValueError(
                f"fragment stream shard width {n_bits} != local {SHARD_WIDTH}"
            )
        with self._mu:
            # pending deltas describe the REPLACED contents; the forced
            # snapshot below truncates their WAL records with everything
            # else, so they must not merge into the new rows. The gen
            # bump invalidates any in-flight barrier snapshot of them.
            self._pending = []
            self._pending_n = 0
            self._pending_gen += 1
            self._premerged = []  # replaced contents: parked layers are void
            self._premerged_n = 0
            if self._captures:
                # a wholesale replace invalidates every in-flight
                # transfer's snapshot+delta contract: force peers to
                # refetch
                self._captures_lost.update(self._captures)
                self._captures.clear()
                self._capture_ns.clear()
            self._rows = rows
            DEVICE_CACHE.invalidate_owner(self._token)
            DEVICE_CACHE.invalidate_owner(self._stack_token)
            self.version += 1
            if self.on_mutate is not None:
                self.on_mutate()
            if self._mutex_map is not None:
                self._rebuild_mutex_map()
            # the rank cache reflects the replaced contents, and snapshot()
            # below persists the sidecar — rebuild before it goes to disk
            self.recalculate_cache()
            self._op_n = self.max_op_n + 1  # force snapshot on next write
            if self.path is not None:
                self.snapshot()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def snapshot(self) -> None:
        """Write full snapshot and reset the WAL
        (reference: fragment.go:2337-2395)."""
        with self._mu:
            # the pending delta MUST merge before the snapshot is written:
            # truncate() below discards its WAL records, so unmerged bits
            # would otherwise be lost
            self._sync_locked()
            if self.path is None:
                self._op_n = 0
                return
            walmod.write_snapshot(self.snap_path, self.shard, SHARD_WIDTH, self._rows)
            if isinstance(self._rows, _LazyRows):
                # offsets moved with the rewrite: re-index unmaterialized
                # rows against the new file (materialized rows unaffected)
                self._rows.rebase(self.snap_path)
            # flush the sidecar BEFORE truncating the WAL: open() trusts
            # the sidecar only when the WAL replayed nothing, so a crash
            # in between leaves a non-empty WAL -> replay -> recalculate,
            # never a stale sidecar served as "provably complete" exact
            # counts (code-review r5 crash-window finding)
            self.flush_cache()
            # crash-matrix injection point: snapshot durable (written,
            # fsynced, dir-synced), WAL not yet truncated — a kill here
            # must replay the full WAL over the fresh snapshot without
            # double-applying (all ops are idempotent re-unions/clears)
            walmod.fault_point("snapshot.pre_truncate", self.snap_path or "")
            if self._wal is not None:
                self._wal.truncate()
            self._op_n = 0
