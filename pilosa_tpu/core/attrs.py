"""Row/column attribute stores.

Reference: /root/reference/attr.go (AttrStore interface) + boltdb/attrstore.go
(BoltDB implementation with block-checksum diffing for anti-entropy). Here:
an in-memory dict persisted as a base JSON snapshot plus a JSONL append log
— each set_attrs appends ONE delta line instead of rewriting the whole
store (the reference gets the same property from BoltDB's page writes,
boltdb/attrstore.go:82-332). The log compacts back into the snapshot once
it grows past COMPACT_THRESHOLD lines. Anti-entropy keeps the same
block/diff shape as the reference (blocks of 100 ids, crc32 checksums,
attr.go:90 AttrBlock.Diff)."""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional

from pilosa_tpu.utils.locks import TrackedRLock

ATTR_BLOCK_SIZE = 100  # reference: attrBlockSize, attr.go

# Log lines before the delta log folds back into the base snapshot. Small
# enough that replay-on-open stays trivial, large enough that steady
# attr-writing amortizes the snapshot rewrite ~4000x.
COMPACT_THRESHOLD = 4096


class AttrStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mu = TrackedRLock("attrs.mu")
        self._attrs: Dict[int, dict] = {}
        self._log_f = None
        self._log_n = 0
        if path is not None:
            if os.path.exists(path):
                with open(path) as f:
                    self._attrs = {int(k): v for k, v in json.load(f).items()}
            self._replay_log()
            if self._log_n >= COMPACT_THRESHOLD:
                self._compact()

    @property
    def _log_path(self) -> str:
        return self.path + ".log"

    # -- reads -------------------------------------------------------------

    def attrs(self, id: int) -> dict:
        with self._mu:
            return dict(self._attrs.get(id, {}))

    def ids(self) -> List[int]:
        with self._mu:
            return sorted(self._attrs)

    # -- writes ------------------------------------------------------------

    def set_attrs(self, id: int, attrs: dict) -> None:
        """Merge attrs; a None value deletes the key (reference semantics)."""
        with self._mu:
            self._apply(id, attrs)
            self._append({str(id): attrs})

    def set_bulk_attrs(self, m: Dict[int, dict]) -> None:
        """Bulk merge; None values are skipped, not deletes (reference
        bulk-import semantics). Normalized before logging so replay can
        use the uniform delete-on-None apply."""
        with self._mu:
            delta = {}
            for id, attrs in m.items():
                clean = {k: v for k, v in attrs.items() if v is not None}
                self._apply(id, clean)
                delta[str(id)] = clean
            self._append(delta)

    def _apply(self, id: int, attrs: dict) -> None:
        cur = self._attrs.setdefault(id, {})
        for k, v in attrs.items():
            if v is None:
                cur.pop(k, None)
            else:
                cur[k] = v

    # -- persistence: base snapshot + JSONL delta log ----------------------

    def _append(self, delta: Dict[str, dict]) -> None:
        if self.path is None:
            return
        if self._log_f is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._log_f = open(self._log_path, "a")
        self._log_f.write(json.dumps(delta, separators=(",", ":")) + "\n")
        self._log_f.flush()
        self._log_n += 1
        if self._log_n >= COMPACT_THRESHOLD:
            self._compact()

    def _replay_log(self) -> None:
        """Apply logged deltas over the base snapshot. A torn final line
        (crash mid-append) is ignored, like the WAL's torn-tail rule — and
        the file is TRUNCATED at the torn offset, so the next append
        starts a fresh line instead of concatenating onto the torn one
        (which would corrupt, and on the following restart silently drop,
        an acknowledged write)."""
        if not os.path.exists(self._log_path):
            return
        valid_end = 0
        with open(self._log_path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break  # torn tail: the append never completed
                try:
                    delta = json.loads(line)
                except json.JSONDecodeError:
                    break
                for id_s, attrs in delta.items():
                    self._apply(int(id_s), attrs)
                self._log_n += 1
                valid_end += len(line)
        if valid_end < os.path.getsize(self._log_path):
            with open(self._log_path, "rb+") as f:
                f.truncate(valid_end)

    def _compact(self) -> None:
        """Fold the delta log into the base snapshot atomically: write the
        full state to .tmp, replace the base, then truncate the log. A
        crash between the two leaves a base that already contains every
        logged delta plus a log whose replay is idempotent re-merging."""
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._attrs.items()}, f)
        os.replace(tmp, self.path)
        if self._log_f is not None:
            self._log_f.close()
        self._log_f = open(self._log_path, "w")
        self._log_n = 0

    def close(self) -> None:
        """Release the append-log fd (Field.close/Index.close call this —
        a long-lived process reopening holders must not leak one fd per
        disk-backed attr store)."""
        with self._mu:
            if self._log_f is not None:
                self._log_f.close()
                self._log_f = None

    # -- anti-entropy support (attr.go:90) ---------------------------------

    def blocks(self) -> List[dict]:
        """Per-block checksums for replica diffing (one pass over the
        store; block_checksum below serves single-block refreshes)."""
        with self._mu:
            by_block: Dict[int, List[int]] = {}
            for id in sorted(self._attrs):
                by_block.setdefault(id // ATTR_BLOCK_SIZE, []).append(id)
            return [
                {"id": b, "checksum": self._checksum_of(ids)}
                for b, ids in sorted(by_block.items())
            ]

    def _checksum_of(self, ids: List[int]) -> int:
        payload = json.dumps(
            [(i, sorted(self._attrs[i].items())) for i in ids]
        ).encode()
        return zlib.crc32(payload)

    def block_data(self, block_id: int) -> Dict[int, dict]:
        with self._mu:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            return {i: dict(a) for i, a in self._attrs.items() if lo <= i < hi}

    def block_checksum(self, block_id: int) -> Optional[int]:
        """Checksum of one block (same serialization as blocks()); None
        when the block holds no attrs. Lets anti-entropy refresh a single
        merged block without re-hashing the whole store."""
        with self._mu:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            ids = sorted(i for i in self._attrs if lo <= i < hi)
            if not ids:
                return None
            return self._checksum_of(ids)
