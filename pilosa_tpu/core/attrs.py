"""Row/column attribute stores.

Reference: /root/reference/attr.go (AttrStore interface) + boltdb/attrstore.go
(BoltDB implementation with block-checksum diffing for anti-entropy). Here:
an in-memory dict with JSON-file persistence and the same block/diff shape
(blocks of 100 ids, xxhash-free checksums via zlib.crc32) so the anti-entropy
layer can sync attrs the same way the reference does (attr.go:90
AttrBlock.Diff)."""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, List, Optional

ATTR_BLOCK_SIZE = 100  # reference: attrBlockSize, attr.go


class AttrStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mu = threading.RLock()
        self._attrs: Dict[int, dict] = {}
        if path is not None and os.path.exists(path):
            with open(path) as f:
                self._attrs = {int(k): v for k, v in json.load(f).items()}

    def attrs(self, id: int) -> dict:
        with self._mu:
            return dict(self._attrs.get(id, {}))

    def set_attrs(self, id: int, attrs: dict) -> None:
        """Merge attrs; a None value deletes the key (reference semantics)."""
        with self._mu:
            cur = self._attrs.setdefault(id, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            self._flush()

    def set_bulk_attrs(self, m: Dict[int, dict]) -> None:
        with self._mu:
            for id, attrs in m.items():
                cur = self._attrs.setdefault(id, {})
                cur.update({k: v for k, v in attrs.items() if v is not None})
            self._flush()

    def ids(self) -> List[int]:
        with self._mu:
            return sorted(self._attrs)

    def _flush(self) -> None:
        if self.path is None:
            return
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._attrs.items()}, f)
        os.replace(tmp, self.path)

    # -- anti-entropy support (attr.go:90) ---------------------------------

    def blocks(self) -> List[dict]:
        """Per-block checksums for replica diffing (one pass over the
        store; block_checksum below serves single-block refreshes)."""
        with self._mu:
            by_block: Dict[int, List[int]] = {}
            for id in sorted(self._attrs):
                by_block.setdefault(id // ATTR_BLOCK_SIZE, []).append(id)
            return [
                {"id": b, "checksum": self._checksum_of(ids)}
                for b, ids in sorted(by_block.items())
            ]

    def _checksum_of(self, ids: List[int]) -> int:
        payload = json.dumps(
            [(i, sorted(self._attrs[i].items())) for i in ids]
        ).encode()
        return zlib.crc32(payload)

    def block_data(self, block_id: int) -> Dict[int, dict]:
        with self._mu:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            return {i: dict(a) for i, a in self._attrs.items() if lo <= i < hi}

    def block_checksum(self, block_id: int) -> Optional[int]:
        """Checksum of one block (same serialization as blocks()); None
        when the block holds no attrs. Lets anti-entropy refresh a single
        merged block without re-hashing the whole store."""
        with self._mu:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            ids = sorted(i for i in self._attrs if lo <= i < hi)
            if not ids:
                return None
            return self._checksum_of(ids)
