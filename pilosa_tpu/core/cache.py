"""Per-fragment row-rank caches for TopN.

Reference: cache.go — `ranked` (sorted bitmapPairs, bounded at cacheSize,
recalculated after a threshold of updates, cache.go:136-300), `lru`
(groupcache fork, cache.go:58-130), `none`; persisted to `.cache` files on a
flush ticker (holder.go:506 monitorCacheFlush, rankCache.WriteTo
cache.go:291).

TPU-first shift: the reference's caches hold *approximate* counts refreshed
from fragment scans. Here row cardinalities are already exact host metadata
(rowstore.RowBits tracks its count), so the cache is pure bookkeeping: it
bounds *which* rows are TopN candidates (top cache_size by count — the same
approximation contract as the reference) while counts stay exact. Unfiltered
TopN then answers from the cache with no device pass at all; filtered TopN
tallies only the cache's candidate rows on device.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Tuple

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

DEFAULT_CACHE_SIZE = 50_000  # reference: field.go:48 DefaultCacheSize

# recalculate/prune after this fraction of cache_size updates
# (reference: cache.go thresholdFactor)
_RECALC_FACTOR = 0.1

# v2 adds the pruned-completeness byte; v1 files fail the magic check and
# the cache rebuilds from exact counts on open (correct, one-time cost)
_MAGIC = b"PTCACHE2"


class RankCache:
    """Bounded row->count map that keeps the top `max_size` rows by count."""

    cache_type = CACHE_TYPE_RANKED

    def __init__(self, max_size: int = DEFAULT_CACHE_SIZE):
        self.max_size = max(int(max_size), 1)
        self._counts: Dict[int, int] = {}
        self._updates = 0
        self._top: Optional[List[Tuple[int, int]]] = None  # desc (count, id)
        # True once any row was dropped for capacity: the cache is then an
        # approximation, not a complete row->count map. TopN's pass-2 fast
        # path reads exact cardinalities straight from an unpruned cache.
        self.pruned = False

    def __len__(self) -> int:
        return len(self._counts)

    def add(self, row_id: int, count: int) -> None:
        """Record a row's (exact) cardinality; count 0 evicts."""
        if count <= 0:
            self._counts.pop(row_id, None)
        else:
            self._counts[row_id] = count
        self._top = None
        self._updates += 1
        if self._updates > self.max_size * _RECALC_FACTOR and (
            len(self._counts) > self.max_size
        ):
            self.recalculate()

    def bulk_add(self, pairs) -> None:
        for row_id, count in pairs:
            if count > 0:
                self._counts[int(row_id)] = int(count)
        self._top = None
        self.recalculate()

    def add_many(self, pairs) -> None:
        """add() for a whole batch with ONE memo drop and ONE threshold
        check — the ingest fast path reconciles every touched row of a
        bulk import here instead of poking the cache once per row."""
        counts = self._counts
        n = 0
        for row_id, count in pairs:
            if count <= 0:
                counts.pop(row_id, None)
            else:
                counts[row_id] = count
            n += 1
        if not n:
            return
        self._top = None
        self._updates += n
        if self._updates > self.max_size * _RECALC_FACTOR and (
            len(counts) > self.max_size
        ):
            self.recalculate()

    def get(self, row_id: int) -> int:
        return self._counts.get(row_id, 0)

    def ids(self) -> List[int]:
        return list(self._counts)

    def recalculate(self) -> None:
        """Prune to the top max_size rows (reference: cache.go:221)."""
        if len(self._counts) > self.max_size:
            keep = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
            self._counts = dict(keep[: self.max_size])
            self.pruned = True
        self._updates = 0
        self._top = None

    def top(self) -> List[Tuple[int, int]]:
        """(row_id, count) pairs, highest count first (ties: lowest id)."""
        if self._top is None:
            self.recalculate()
            self._top = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return self._top

    def clear(self) -> None:
        self._counts.clear()
        self._updates = 0
        self._top = None
        self.pruned = False


class LRUCache(RankCache):
    """Recently-updated-rows cache: same interface, but the bound evicts the
    least recently *added* row instead of the lowest count
    (reference: cache.go:58-130 lruCache)."""

    cache_type = CACHE_TYPE_LRU

    def add(self, row_id: int, count: int) -> None:
        if count <= 0:
            self._counts.pop(row_id, None)
        else:
            # dict preserves insertion order; re-insert = touch
            self._counts.pop(row_id, None)
            self._counts[row_id] = count
            self._evict()
        self._top = None

    def add_many(self, pairs) -> None:
        # recently-updated semantics need the per-add touch/evict order
        for row_id, count in pairs:
            self.add(row_id, count)

    def _evict(self) -> None:
        while len(self._counts) > self.max_size:
            self._counts.pop(next(iter(self._counts)))
            self.pruned = True

    def recalculate(self) -> None:
        self._evict()  # bulk loads must still honor the lru bound
        self._updates = 0
        self._top = None


class NoCache:
    """cache_type 'none': TopN is disabled on the field."""

    cache_type = CACHE_TYPE_NONE
    max_size = 0

    def __len__(self) -> int:
        return 0

    def add(self, row_id: int, count: int) -> None:
        pass

    def bulk_add(self, pairs) -> None:
        pass

    def add_many(self, pairs) -> None:
        pass

    def get(self, row_id: int) -> int:
        return 0

    def ids(self) -> List[int]:
        return []

    def recalculate(self) -> None:
        pass

    def top(self) -> List[Tuple[int, int]]:
        return []

    def clear(self) -> None:
        pass


def make_cache(cache_type: str, size: int = DEFAULT_CACHE_SIZE):
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    if cache_type == CACHE_TYPE_NONE:
        return NoCache()
    raise ValueError(f"unknown cache type: {cache_type!r}")


# -- persistence (.cache sidecar; reference cache.go:291 WriteTo) -----------


def write_cache(path: str, cache) -> None:
    pairs = cache.top()
    tmp = path + ".temp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        # the completeness flag must survive restarts: a pruned cache
        # reloaded as "complete" would let cache_counts_exact() return 0
        # for rows the sidecar dropped (silent TopN undercounts)
        f.write(struct.pack("<BI", 1 if cache.pruned else 0, len(pairs)))
        for row_id, count in pairs:
            f.write(struct.pack("<QQ", row_id, count))
    os.replace(tmp, path)


def read_cache(path: str, cache) -> bool:
    """Load pairs into `cache`; False if the file is absent/unreadable."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    if len(data) < 13 or data[:8] != _MAGIC:
        return False
    pruned, n = struct.unpack_from("<BI", data, 8)
    if len(data) < 13 + 16 * n:
        return False
    pairs = []
    for i in range(n):
        row_id, count = struct.unpack_from("<QQ", data, 13 + 16 * i)
        pairs.append((row_id, count))
    cache.clear()
    cache.bulk_add(pairs)
    if pruned:
        cache.pruned = True
    return True
