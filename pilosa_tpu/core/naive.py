"""Naive set-model bitmap — the differential-testing oracle.

Reference: /root/reference/roaring/naive.go (a deliberately simple uint64-slice
bitmap used by the go-fuzz differential harness, roaring/fuzzer.go:37). Every
device kernel and storage layer in this package is tested against this model.

Semantics are plain set algebra over uint64 positions. Nothing here is
performance-relevant; clarity wins.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List


class NaiveBitmap:
    """A bitmap over 64-bit positions backed by a Python set."""

    __slots__ = ("_bits",)

    def __init__(self, positions: Iterable[int] = ()):
        self._bits = set(positions)
        for p in self._bits:
            if p < 0:
                raise ValueError(f"negative position {p}")

    # -- mutation ---------------------------------------------------------

    def add(self, *positions: int) -> bool:
        """Add positions; returns True if anything changed."""
        for p in positions:
            if p < 0:
                raise ValueError(f"negative position {p}")
        before = len(self._bits)
        self._bits.update(positions)
        return len(self._bits) != before

    def remove(self, *positions: int) -> bool:
        before = len(self._bits)
        self._bits.difference_update(positions)
        return len(self._bits) != before

    # -- queries ----------------------------------------------------------

    def contains(self, p: int) -> bool:
        return p in self._bits

    def count(self) -> int:
        return len(self._bits)

    def count_range(self, start: int, stop: int) -> int:
        return sum(1 for p in self._bits if start <= p < stop)

    def slice(self) -> List[int]:
        return sorted(self._bits)

    def slice_range(self, start: int, stop: int) -> List[int]:
        return sorted(p for p in self._bits if start <= p < stop)

    def max(self) -> int:
        return max(self._bits) if self._bits else 0

    def min(self) -> int:
        return min(self._bits) if self._bits else 0

    def any(self) -> bool:
        return bool(self._bits)

    def __iter__(self) -> Iterator[int]:
        return iter(sorted(self._bits))

    def __eq__(self, other) -> bool:
        return isinstance(other, NaiveBitmap) and self._bits == other._bits

    def __repr__(self) -> str:
        return f"NaiveBitmap({sorted(self._bits)[:16]}{'...' if len(self._bits) > 16 else ''})"

    # -- set algebra -------------------------------------------------------

    def intersect(self, other: "NaiveBitmap") -> "NaiveBitmap":
        return NaiveBitmap(self._bits & other._bits)

    def union(self, *others: "NaiveBitmap") -> "NaiveBitmap":
        out = set(self._bits)
        for o in others:
            out |= o._bits
        return NaiveBitmap(out)

    def difference(self, *others: "NaiveBitmap") -> "NaiveBitmap":
        out = set(self._bits)
        for o in others:
            out -= o._bits
        return NaiveBitmap(out)

    def xor(self, other: "NaiveBitmap") -> "NaiveBitmap":
        return NaiveBitmap(self._bits ^ other._bits)

    def intersection_count(self, other: "NaiveBitmap") -> int:
        return len(self._bits & other._bits)

    def shift(self, n: int = 1) -> "NaiveBitmap":
        """Shift all positions up by n (reference: roaring shift, roaring.go:4579)."""
        return NaiveBitmap(p + n for p in self._bits if p + n >= 0)

    def flip(self, start: int, stop: int) -> "NaiveBitmap":
        """Flip bits in [start, stop] inclusive (reference flip semantics)."""
        return NaiveBitmap(self._bits ^ set(range(start, stop + 1)))

    def offset_range(self, offset: int, start: int, end: int) -> "NaiveBitmap":
        """Positions in [start, end) rebased to offset (reference:
        roaring.go OffsetRange — used to lift a fragment row into the global
        column space)."""
        return NaiveBitmap(p - start + offset for p in self._bits if start <= p < end)
