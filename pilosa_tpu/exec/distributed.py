"""Distributed executor: cluster fan-out + per-call reduce + failover.

Reference: /root/reference/executor.go:2460-2613 — mapReduce groups shards
by owner node, runs the local subset on the worker pool and ships remote
subsets as Remote=true queries (executor.go:2419 remoteExec); the reduce
loop merges partial results as they arrive and, when a node errors, re-maps
its shards onto surviving replicas (executor.go:2489-2518).

Structure here: DistributedExecutor subclasses the single-node Executor and
intercepts exactly the per-call entry points. A "partial" is the result of
one call restricted to one node's shard subset, executed with remote
semantics (no translation, untrimmed TopN candidates); `_fan_out` computes
partials (local subset via super(), remote via InternalClient) and
`_reduce` folds them per result type — the same shape the reference's
reduceFn table has. TopN keeps its exact two-pass protocol because pass 1/
pass 2 each go through the overridden `_topn_shards` fan-out.

Write calls route by ownership: single-column writes go to every replica
owner of the column's shard (executor.go:2142-2172 fan-out to owners);
row-wide writes (ClearRow/Store) run on every node over its owned shards;
attr writes replicate to all nodes.

Mesh-group execution (exec/meshgroup.py): read fan-outs first fold every
owner node sharing this node's ICI domain (topology mesh_group + the
process-local registry, parallel/mesh.py) into ONE compiled sharded
program with the reduction in program — one dispatch + one blocking host
read for the whole group instead of one HTTP leg per member. HTTP/DCN
legs remain the transport only for nodes OUTSIDE the group; any
mesh-ineligible shape falls back to legs transparently."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

from pilosa_tpu.utils import tracing
from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.executor import (
    ExecError,
    ExecOptions,
    Executor,
    GroupCount,
    Pair,
    ValCount,
)
from pilosa_tpu.pql.ast import Call
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.stats import NopStatsClient


def _faults():
    # lazy: pilosa_tpu.server.__init__ imports node -> this module, so a
    # top-level "from pilosa_tpu.server import faults" would be circular
    # when exec.distributed is imported before the server package
    from pilosa_tpu.server import faults

    return faults

DEFAULT_QUERY_DEADLINE = 30.0


class RemoteError(ExecError):
    """A remote node failed to execute its shard subset."""


class DistributedExecutor(Executor):
    def __init__(
        self,
        holder: Holder,
        cluster_fn: Callable[[], Cluster],
        client,
        local_id: str,
        stats=None,
        query_deadline: float = DEFAULT_QUERY_DEADLINE,
        mesh_min_nodes: int = 2,
    ):
        super().__init__(holder)
        self.cluster_fn = cluster_fn
        self.client = client
        self.local_id = local_id
        self.stats = stats if stats is not None else NopStatsClient()
        # overall wall-clock bound on one distributed call's fan-out,
        # covering every re-map round and backoff (config: query-deadline)
        self.query_deadline = query_deadline
        # mesh-group execution ([mesh] min-nodes knob): group-local owner
        # nodes below this count keep their HTTP legs (folding a single
        # node buys nothing); 0 disables the mesh path entirely
        self.mesh_min_nodes = mesh_min_nodes
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_mu = TrackedLock("distributed.pool_mu")
        # coherence plane (pilosa_tpu/coherence/): set by NodeServer when
        # [coherence] is enabled. A live lease mirror answers remote
        # version vectors with zero wire round-trips; None = every remote
        # repeat pays the /internal/versions RPC as before.
        self.coherence = None

    def _fanout_pool(self) -> ThreadPoolExecutor:
        """Lazy shared pool for concurrent per-node requests (the role of
        the reference's one-mapper-goroutine-per-node, executor.go:2522).
        Lock-guarded: concurrent first queries must not leak duplicate
        pools (HTTP handler threads share this executor)."""
        with self._pool_mu:
            if self._pool is None:
                # owns: released by close() from NodeServer.stop()
                self._pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix=f"fanout-{self.local_id}"
                )
            return self._pool

    def close(self) -> None:
        """Release the lazy fan-out pool. NodeServer.stop() calls this;
        before it did, every server start/stop cycle stranded up to 16
        idle fanout-* threads for the life of the process."""
        with self._pool_mu:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # ------------------------------------------------------------------
    # fan-out plumbing
    # ------------------------------------------------------------------

    def _cluster(self) -> Cluster:
        return self.cluster_fn()

    def _is_single_node(self) -> bool:
        return len(self._cluster().nodes) <= 1

    def _uri_of(self, node_id: str) -> str:
        n = self._cluster().node_by_id(node_id)
        if n is None:
            raise RemoteError(f"unknown node {node_id}")
        return n.uri

    def _breaker_open(self, uri: str) -> bool:
        faults = _faults()
        breakers = getattr(self.client, "breakers", None) or faults.global_breakers()
        return breakers is not None and breakers.state(uri) == faults.OPEN

    def _fan_out(
        self, idx: Index, c: Call, shards: Optional[Sequence[int]], write: bool = False
    ) -> List[Any]:
        """Run call `c` over the cluster's shards; returns the list of
        partial results (local partial included). Reads go to the first
        live owner per shard with failover re-mapping (executor.go:2497);
        writes go to EVERY live replica owner (executor.go:2142).

        The whole fan-out — every re-map round and backoff included — is
        bounded by `query_deadline`; re-map rounds back off with the
        client's retry policy, and owner selection prefers replicas whose
        circuit breaker is not open (a known-dead peer only gets picked
        when every replica looks dead)."""
        cluster = self._cluster()
        all_shards = self._shards_for(idx, shards, c)
        if write:
            remaining = dict(cluster.shards_by_all_owners(idx.name, all_shards))
        else:
            remaining = dict(cluster.shards_by_node(idx.name, all_shards))
        policy = getattr(self.client, "retry_policy", None) or _faults().RetryPolicy()
        deadline = policy.budget(self.query_deadline)
        partials: List[Any] = []
        failed: set = set()
        attempts = 0
        # flight recorder: one exec.fanout span covers the whole fan-out
        # (all re-map rounds); each per-node request runs inside its own
        # rpc.leg child, ENTERED ON THE POOL THREAD so the internode
        # client sees it as the current span — that is what propagates
        # the trace headers to the peer and hosts the rpc.retries /
        # breaker tags (the pool thread has no inherited contextvars)
        fspan = tracing.start_span("exec.fanout")
        fspan.set_tag("fanout.call", c.name)
        fspan.set_tag("fanout.shards", len(all_shards))
        if write:
            fspan.set_tag("fanout.write", True)
        with fspan:
            # mesh-group fold: owner nodes sharing this node's ICI domain
            # answer as ONE compiled sharded program (exec/meshgroup.py)
            # instead of one HTTP leg each; ineligible shapes fall back to
            # legs below, transparently
            if not write and remaining:
                mesh_nodes = self._mesh_group_nodes(remaining)
                if mesh_nodes and self._mesh_eligible(c):
                    from pilosa_tpu.exec import meshgroup

                    try:
                        partials.append(
                            self._mesh_group_partial(idx, c, mesh_nodes, fspan)
                        )
                    except meshgroup.MeshUnsupported as e:
                        meshgroup.note_fallback()
                        # reason-tagged fallback counter: a silent drop
                        # to HTTP legs is a 5-9x latency regression that
                        # must be visible on dashboards
                        self.stats.with_tags(
                            f"reason:{getattr(e, 'reason', 'unsupported')}"
                        ).count("mesh.fallback")
                    else:
                        for nid in mesh_nodes:
                            remaining.pop(nid, None)
                        fspan.set_tag("fanout.mesh_nodes", len(mesh_nodes))
            while remaining:
                attempts += 1
                if attempts > len(cluster.nodes) + 1:
                    raise RemoteError("shards could not be placed on any live node")
                if deadline.expired():
                    raise RemoteError(
                        f"query deadline ({self.query_deadline}s) exceeded with "
                        f"shards unplaced on nodes {sorted(remaining)}"
                    )
                if attempts > 1:
                    # breathe between re-map rounds: a replica refusing
                    # connections during a restart needs milliseconds, not an
                    # instant second hammering (bounded by the deadline)
                    delay = min(policy.backoff(attempts - 1), deadline.remaining())
                    if delay > 0:
                        policy.sleep(delay)
                # one concurrent request per node (executor.go:2522 mapper
                # goroutines): a slow node no longer serializes the others.
                # RemoteErrors come back as values so failover re-mapping
                # inspects every node's outcome; other exceptions propagate.
                items = list(remaining.items())

                def attempt(t):
                    node_id, node_shards = t
                    with tracing.start_span("rpc.leg", parent=fspan) as leg:
                        leg.set_tag("peer", node_id)
                        leg.set_tag(
                            "leg.local", node_id == self.local_id
                        )
                        leg.set_tag("leg.shards", len(node_shards))
                        try:
                            # each RPC is bounded by the query deadline's
                            # REMAINING time, so a hung (connected-but-
                            # silent) peer cannot stall the fan-out past
                            # the deadline
                            return self._node_partial(
                                idx,
                                c,
                                node_id,
                                node_shards,
                                write=write,
                                timeout=max(0.05, deadline.remaining()),
                                # the peer's admission controller sheds
                                # this leg (429, retryable) when OUR
                                # remaining budget can no longer be met
                                # in its queue
                                deadline=max(0.05, deadline.remaining()),
                            )
                        except RemoteError as e:
                            leg.set_tag("leg.error", str(e)[:200])
                            return e

                if len(items) == 1:
                    outcomes = [attempt(items[0])]
                else:
                    outcomes = list(self._fanout_pool().map(attempt, items))
                retry: Dict[str, List[int]] = {}
                for (node_id, node_shards), res in zip(items, outcomes):
                    if not isinstance(res, RemoteError):
                        partials.append(res)
                        continue
                    failed.add(node_id)
                    if write:
                        # replicas already targeted; drift repairs via
                        # anti-entropy — but the debt must be VISIBLE: record
                        # each dropped (index, shard, replica) for /status and
                        # bump the drop counter (ISSUE satellite #2). Ledger
                        # entries only exist at replica_n>1: with no second
                        # copy there is nothing for AE to repair FROM, so an
                        # entry could never drain (the error surfaces through
                        # the call's own result/logs instead).
                        if cluster.replica_n > 1:
                            for s in node_shards:
                                self.holder.record_pending_repair(
                                    idx.name, s, node_id
                                )
                            self.stats.count(
                                "write_replica_dropped", len(node_shards)
                            )
                        continue
                    # re-map this node's shards to the next live replica,
                    # preferring replicas whose breaker is closed
                    for s in node_shards:
                        owners = [
                            n
                            for n in cluster.shard_nodes(idx.name, s)
                            if n.id not in failed and n.state != "DOWN"
                        ]
                        if not owners:
                            raise RemoteError(
                                f"shard {s} unavailable: all replicas down"
                            )
                        owners.sort(
                            key=lambda n: n.id != self.local_id
                            and self._breaker_open(n.uri)
                        )
                        retry.setdefault(owners[0].id, []).append(s)
                remaining = retry
            fspan.set_tag("fanout.rounds", attempts)
            if failed:
                fspan.set_tag("fanout.failed_peers", sorted(failed))
        return partials

    # ------------------------------------------------------------------
    # mesh-group execution (exec/meshgroup.py)
    # ------------------------------------------------------------------

    def _mesh_group(self) -> str:
        """This node's ICI-domain id per the installed topology ([mesh]
        group knob, carried on every topology install)."""
        return self._cluster().mesh_group_of(self.local_id)

    def _mesh_members(self) -> Dict[str, Any]:
        """node_id -> holder for every group member reachable in-process
        (the registry, parallel/mesh.py) — the local node always is."""
        from pilosa_tpu.parallel import mesh as pmesh

        group = self._mesh_group()
        if not group or self.mesh_min_nodes <= 0:
            return {}
        members = pmesh.group_members(group)
        members[self.local_id] = self.holder
        return members

    def _mesh_group_nodes(
        self, remaining: Dict[str, List[int]]
    ) -> Dict[str, List[int]]:
        """The subset of a read fan-out's owner grouping answerable as one
        mesh-group dispatch: nodes declaring this node's mesh group in the
        topology AND registered in the process-local registry (sharing an
        ICI domain means sharing this process's device mesh). Below the
        min-nodes knob the fold buys nothing over plain legs — {}."""
        members = self._mesh_members()
        if not members:
            return {}
        cluster = self._cluster()
        group = self._mesh_group()
        out = {
            nid: shards
            for nid, shards in remaining.items()
            if nid in members and cluster.mesh_group_of(nid) == group
        }
        # the knob is honored as documented: min-nodes=1 folds even a
        # single group-local owner (saving its HTTP leg when it is a
        # peer); the default of 2 skips the adapter overhead when only
        # this node's own shards are in play
        if len(out) < max(1, self.mesh_min_nodes):
            return {}
        return out

    def _mesh_eligible(self, c: Call) -> bool:
        from pilosa_tpu.exec import meshgroup

        return meshgroup.eligible(c)

    def _mesh_group_index(self, idx: Index, mesh_nodes: Dict[str, List[int]]):
        from pilosa_tpu.exec import meshgroup

        return meshgroup.group_index(idx, self._mesh_members(), mesh_nodes)

    def _mesh_group_partial(
        self, idx: Index, c: Call, mesh_nodes: Dict[str, List[int]], fspan
    ) -> Any:
        """One partial for the WHOLE mesh group: the unchanged single-node
        execution over a group-spanning index adapter, so the result is
        bit-identical to merging the members' per-leg partials (the merge
        is associative) while the device work is one compiled program.
        Count ends in the in-program reduction (plan "total" mode) — one
        dispatch + one scalar-sized blocking read regardless of group
        shard count."""
        from pilosa_tpu.exec import meshgroup

        gidx = self._mesh_group_index(idx, mesh_nodes)
        shard_list = sorted(s for lst in mesh_nodes.values() for s in lst)
        span = tracing.start_span("exec.mesh_dispatch", parent=fspan)
        with span:
            span.set_tag("mesh.group_size", len(mesh_nodes))
            span.set_tag("mesh.local_shards", len(shard_list))
            span.set_tag("mesh.call", c.name)
            if c.name == "Count":
                result, cbytes = meshgroup.mesh_count(self, gidx, c, shard_list)
            else:
                # TopN tallies and bitmap trees ride the unchanged local
                # execution paths over the group adapter (remote
                # semantics: untrimmed candidates, no attr/translate tail)
                result = Executor._execute_call(
                    self, gidx, c, shard_list, ExecOptions(remote=True)
                )
                from pilosa_tpu.shardwidth import WORDS_PER_ROW

                # a row-shaped result gathers its [S, W] stack; tallies
                # and counts read shard-count-bound vectors
                cbytes = (
                    len(shard_list) * WORDS_PER_ROW * 4
                    if isinstance(result, Row)
                    else len(shard_list) * 8
                )
            span.set_tag("mesh.collective_bytes", cbytes)
            meshgroup.note_dispatch(len(mesh_nodes), len(shard_list), cbytes)
        return result

    def _execute_count_batch(
        self, idx: Index, calls: List[Call], shards, opt: Optional[ExecOptions] = None
    ):
        """Coordinator-side multi-Count batching: legal only when EVERY
        call's owners fold into one mesh-group dispatch (operands of the
        mesh and extent paths have incompatible placements — the batcher
        splits its rounds by lowering class for exactly this reason).
        Remote legs and single-node execution keep the local lowering."""
        if (opt is not None and opt.remote) or self._is_single_node():
            return super()._execute_count_batch(idx, calls, shards, opt)
        from pilosa_tpu.exec import meshgroup

        cluster = self._cluster()
        lists = [self._shards_for(idx, shards, c) for c in calls]
        if any(lst != lists[0] for lst in lists[1:]):
            return None
        if not all(self._mesh_eligible(c) for c in calls):
            return None
        remaining = dict(cluster.shards_by_node(idx.name, lists[0]))
        mesh_nodes = self._mesh_group_nodes(remaining)
        if set(mesh_nodes) != set(remaining):
            return None  # cross-group legs present: per-call fan-out
        gidx = self._mesh_group_index(idx, mesh_nodes)
        shard_list = sorted(s for lst in mesh_nodes.values() for s in lst)
        span = tracing.start_span("exec.mesh_dispatch")
        try:
            with span:
                span.set_tag("mesh.group_size", len(mesh_nodes))
                span.set_tag("mesh.local_shards", len(shard_list))
                span.set_tag("mesh.call", f"Count[{len(calls)}]")
                totals, cbytes = meshgroup.mesh_count_batch(
                    self, gidx, calls, shard_list
                )
                span.set_tag("mesh.collective_bytes", cbytes)
                meshgroup.note_dispatch(len(mesh_nodes), len(shard_list), cbytes)
                return totals
        except meshgroup.MeshUnsupported as e:
            meshgroup.note_fallback()
            self.stats.with_tags(
                f"reason:{getattr(e, 'reason', 'unsupported')}"
            ).count("mesh.fallback")
            return None

    # ------------------------------------------------------------------
    # versioned result cache: assembled version vectors (core/resultcache)
    # ------------------------------------------------------------------

    def version_vector(self, idx: Index, ctx, opt: ExecOptions, expect=None):
        """The fan-out's assembled version vector: per owner node, the
        versions of the fragments its partial would read — local and
        in-process mesh members by direct (lock-free) reads, remote
        peers over one parallel /internal/versions round. Per-node shard
        lists are Shift-extended exactly like the legs' execution, so
        the vector covers every fragment a leg actually touches. None =
        uncacheable this round (unreachable peer, first sighting of an
        RPC-vector key, topology lookup failure). `expect` (the
        store-path guard's pre-execution vector): when the CHEAP
        in-process parts already diverge from it — continuous local
        ingest racing the query — bail before paying the remote RPC
        round for a store that cannot succeed."""
        if opt.remote or self._is_single_node():
            return super().version_vector(idx, ctx, opt)
        from pilosa_tpu.core import resultcache as rcache

        cluster = self._cluster()
        try:
            remaining = dict(
                cluster.shards_by_node(idx.name, list(ctx.shard_list))
            )
        except Exception:  # noqa: BLE001 - assembly is best-effort
            return None
        members = self._mesh_members()
        parts: List[Any] = []
        rpc: List[tuple] = []
        for nid in sorted(remaining):
            node_shards = tuple(
                Executor._shards_for(
                    self, idx, sorted(remaining[nid]), ctx.call
                )
            )
            if nid == self.local_id:
                parts.append(
                    self.local_version_vector(
                        idx, ctx.views, node_shards, node=nid
                    )
                )
            elif nid in members:
                idx2 = members[nid].index(idx.name)
                if idx2 is None:
                    return None
                parts.append(
                    self.local_version_vector(
                        idx2, ctx.views, node_shards, node=nid
                    )
                )
            else:
                rpc.append((nid, node_shards))
                parts.append(None)
        if rpc:
            if expect is not None and not self._parts_match_expect(
                parts, expect, len(ctx.views)
            ):
                return None
            mgr = self.coherence
            if mgr is not None and mgr.leases_enabled:
                fetched = self._leased_remote_versions(idx, ctx, rpc, mgr)
            else:
                # remote versions cost one RTT per peer: only repeat keys
                # pay it (a one-off query would be taxed for nothing)
                if not rcache.RESULT_CACHE.note_candidate(ctx.key):
                    return None
                fetched = self._fetch_remote_versions(idx, ctx, rpc)
            if fetched is None:
                return None
            it = iter(fetched)
            parts = [next(it) if p is None else p for p in parts]
        out: List[tuple] = []
        for elems in parts:
            out.extend(elems)
        return tuple(out)

    def clock_vector(self, idx: Index, ctx, opt: ExecOptions):
        """The O(#views) clock fast path applies only where every clock
        is readable in-process (single node, remote legs): coordinator
        entries span peers whose clocks live behind the same RPC the
        exact vector rides, so the fast path would save nothing."""
        if opt.remote or self._is_single_node():
            return super().clock_vector(idx, ctx, opt)
        return None

    @staticmethod
    def _parts_match_expect(parts, expect, views_per_node) -> bool:
        """Whether every already-collected (in-process) per-node part
        equals its positional slice of `expect` — each node contributes
        exactly one element per referenced view, so slices align unless
        the assignment itself changed (then the mismatch is the right
        answer too)."""
        o = 0
        for p in parts:
            if p is not None and tuple(expect[o:o + views_per_node]) != p:
                return False
            o += views_per_node
        return True

    def _leased_remote_versions(self, idx: Index, ctx, rpc, mgr):
        """Lease-plane replacement for the per-peer version round: a
        live mirror answers a peer's element slice with ZERO wire RTTs;
        uncovered peers try one lease acquire (which replaces this
        round's version RPC and every later one — the mirror then
        serves ALL keys over this (peer, index)) before degrading to
        the plain fetch. Deliberately NO note_candidate gate: the lease
        is per-(peer, index) and amortizes across every key, so even a
        first-sighted key rides it — and because mirror elements are
        bit-identical to /internal/versions elements, a fresh grant
        retro-covers entries stored from earlier RPC vectors (the
        second hit after lease grant is already RTT-free, not the
        third). coherence.version_rtts counts only the rounds that
        still paid a wire fetch."""
        need: List[tuple] = []
        slots: Dict[int, tuple] = {}
        for pos, (nid, node_shards) in enumerate(rpc):
            # the peer extends the shard list it receives by the call's
            # Shift count before reading versions (versions_payload);
            # mirror reads must cover the same extended axis to stay
            # element-identical with fetched vectors
            ext = tuple(
                Executor._shards_for(self, idx, sorted(node_shards), ctx.call)
            )
            elems = mgr.mirror_elements(nid, idx.name, ctx.views, ext)
            if elems is None and mgr.acquire(
                nid, self._uri_of(nid), idx.name
            ):
                elems = mgr.mirror_elements(nid, idx.name, ctx.views, ext)
            if elems is None:
                need.append((nid, node_shards))
            else:
                slots[pos] = elems
        if need:
            mgr.count_version_rtt(len(need))
            fetched = self._fetch_remote_versions(idx, ctx, need)
            if fetched is None:
                return None
            it = iter(fetched)
            for pos in range(len(rpc)):
                if pos not in slots:
                    slots[pos] = next(it)
        return [slots[pos] for pos in range(len(rpc))]

    def _fetch_remote_versions(self, idx: Index, ctx, rpc):
        """One parallel /internal/versions round; None when any peer is
        unreachable or reports the call ineligible on its side."""
        def fetch(t):
            nid, node_shards = t
            try:
                resp = self.client.fragment_versions(
                    self._uri_of(nid), idx.name, ctx.text, list(node_shards)
                )
            except Exception:  # noqa: BLE001 - degrade to uncacheable
                return None
            if not isinstance(resp, dict) or resp.get("views") is None:
                return None
            boot = str(resp.get("boot", ""))
            try:
                shards = tuple(int(s) for s in resp.get("shards", node_shards))
                elems = []
                for item in resp["views"]:
                    if item[0] == "m":
                        elems.append(("m", nid, item[1], item[2]))
                    else:
                        elems.append(
                            ("v", nid, item[1], item[2],
                             (boot, int(item[3])), shards,
                             tuple(int(x) for x in item[4]))
                        )
                return tuple(elems)
            except Exception:  # noqa: BLE001 - malformed peer payload
                return None

        if len(rpc) == 1:
            fetched = [fetch(rpc[0])]
        else:
            fetched = list(self._fanout_pool().map(fetch, rpc))
        if any(f is None for f in fetched):
            return None
        return fetched

    def versions_payload(self, index_name: str, pql: str, shards):
        """Serve /internal/versions (server/handler.py): this node's
        version vector for one call over `shards`, Shift-extended the
        way a leg's execution would extend them. Returns (shard_list,
        elements) or None when the call is cache-ineligible here."""
        idx = self.holder.index(index_name)
        if idx is None:
            return None
        from pilosa_tpu.pql import parse
        from pilosa_tpu.pql.parser import ParseError

        try:
            q = parse(pql)
        except ParseError:
            return None
        if len(q.calls) != 1:
            return None
        c = q.calls[0]
        ctx = self._cache_spec(
            idx, c, list(shards), ExecOptions(remote=True)
        )
        if ctx is None:
            return None
        shard_list = tuple(
            Executor._shards_for(self, idx, sorted(int(s) for s in shards), c)
        )
        out = []
        for elem in self.local_version_vector(idx, ctx.views, shard_list):
            if elem[0] == "m":
                out.append(["m", elem[2], elem[3]])
            else:
                out.append(["v", elem[2], elem[3], elem[4], list(elem[6])])
        return list(shard_list), out

    def count_lowering_class(self, index_name: str, query) -> str:
        """Which lowering a pure-Count query's batch round would ride:
        "mesh" when every call folds into one mesh-group dispatch,
        "fanout" when any call needs HTTP legs, "local" on a single node.
        The CountBatcher splits its group-commit rounds by this key —
        merging a mesh-path Count with a fan-out Count into one multi-root
        plan would hand XLA operands with incompatible placements.
        Classification must never fail a query: errors degrade to
        "fanout" (per-call execution is always correct)."""
        try:
            if self._is_single_node():
                return "local"
            idx = self.holder.index(index_name)
            if idx is None:
                return "fanout"
            cluster = self._cluster()
            for c in query.calls:
                if not self._mesh_eligible(c):
                    return "fanout"
                shard_list = self._shards_for(idx, None, c)
                remaining = dict(cluster.shards_by_node(idx.name, shard_list))
                mesh_nodes = self._mesh_group_nodes(remaining)
                if set(mesh_nodes) != set(remaining):
                    return "fanout"
            return "mesh"
        except Exception:  # noqa: BLE001 - classification is advisory
            return "fanout"

    def transport_profile(self, idx: Index, shards=None) -> Optional[Dict[str, int]]:
        """Admission-time transport split for sched/cost.py's collective
        terms: how many of the query's shards fold into the mesh-group
        collective vs ride cross-group HTTP legs. `device_shards` is the
        shard axis THIS node's device actually materializes — the whole
        group's shards when the fold engages (the one compiled program
        stages every member's operands here, while the members admit no
        leg) plus the local-only share — which the api layer feeds to the
        cost estimator so a mesh dispatch is byte-charged in full, not at
        the coordinator's 1/N share. Metadata walk only; failures degrade
        to None (the caller keeps its local-share heuristic)."""
        try:
            if self._is_single_node():
                return {
                    "mesh_shards": 0, "legs": 0, "leg_shards": 0,
                    "device_shards": 0,
                }
            all_shards = self._shards_for(idx, shards, None)
            remaining = dict(
                self._cluster().shards_by_node(idx.name, all_shards)
            )
            mesh_nodes = self._mesh_group_nodes(remaining)
            mesh_shards = sum(len(v) for v in mesh_nodes.values())
            # the local node's own share crosses no link: it is neither a
            # DCN leg nor (unless folded with peers) a collective
            legs = [
                n
                for n in remaining
                if n not in mesh_nodes and n != self.local_id
            ]
            leg_shards = sum(len(remaining[n]) for n in legs)
            local_only = (
                0
                if self.local_id in mesh_nodes
                else len(remaining.get(self.local_id, []))
            )
            return {
                "mesh_shards": mesh_shards,
                "legs": len(legs),
                "leg_shards": leg_shards,
                "device_shards": mesh_shards + local_only,
            }
        except Exception:  # noqa: BLE001 - estimation must never fail
            return None

    def _node_partial(
        self,
        idx: Index,
        c: Call,
        node_id: str,
        node_shards: List[int],
        write: bool = False,
        timeout: Optional[float] = None,
        deadline: Optional[float] = None,
    ) -> Any:
        if node_id == self.local_id:
            opt = ExecOptions(remote=True)
            return super()._execute_call(idx, c, node_shards, opt)
        try:
            results = self.client.query_node(
                self._uri_of(node_id),
                idx.name,
                str(c),
                shards=node_shards,
                remote=True,
                timeout=timeout,
                deadline=deadline,
            )
        except Exception as e:
            # reads: node-down shaped failures fail over to a replica; a
            # non-retryable ClientError (4xx / remote payload error) means
            # the peer is alive and rejected the request — replaying the
            # same bad request on a replica cannot succeed (satellite #1).
            # writes: EVERY failure stays RemoteError-shaped so the write
            # path records pending-repair debt for this replica and keeps
            # going instead of aborting the fan-out mid-flight with other
            # replicas already written.
            if write or getattr(e, "retryable", True):
                raise RemoteError(f"node {node_id}: {e}") from e
            raise ExecError(f"node {node_id}: {e}") from e
        return results[0]

    # ------------------------------------------------------------------
    # reduce table
    # ------------------------------------------------------------------

    @staticmethod
    def _reduce_rows(partials: List[Any]) -> Row:
        out = Row()
        for p in partials:
            if isinstance(p, Row):
                out = out.union(p)
        return out

    def _reduce(self, name: str, c: Call, partials: List[Any]) -> Any:
        partials = [p for p in partials if p is not None]
        if name in (
            "Row", "Union", "Intersect", "Difference", "Xor", "Not",
            "Shift", "Range", "All",
        ):
            return self._reduce_rows(partials)
        if name == "Count":
            return sum(int(p) for p in partials)
        if name in ("Clear", "ClearRow", "Store"):
            return any(bool(p) for p in partials)
        if name == "Sum":
            vc = ValCount(0, 0)
            for p in partials:
                vc = ValCount(vc.value + p.value, vc.count + p.count)
            return vc
        if name in ("Min", "Max"):
            best: Optional[ValCount] = None
            for p in partials:
                if p.count == 0:
                    continue
                if best is None:
                    best = ValCount(p.value, p.count)
                elif (p.value < best.value) == (name == "Min") and p.value != best.value:
                    best = ValCount(p.value, p.count)
                elif p.value == best.value:
                    best = ValCount(best.value, best.count + p.count)
            return best or ValCount(0, 0)
        if name in ("MinRow", "MaxRow"):
            best = None
            for p in partials:
                if not p or p.get("count", 0) == 0:
                    continue
                if best is None:
                    best = dict(p)
                elif p["id"] == best["id"]:
                    best["count"] += p["count"]
                elif (p["id"] < best["id"]) == (name == "MinRow"):
                    best = dict(p)
            return best or {"id": 0, "count": 0}
        if name == "Rows":
            merged = set()
            for p in partials:
                merged.update(p)
            out = sorted(merged)
            limit = c.uint_arg("limit")
            prev = c.uint_arg("previous")
            if prev is not None:
                out = [r for r in out if r > prev]
            if limit is not None:
                out = out[:limit]
            return out
        if name == "GroupBy":
            merged: Dict[tuple, GroupCount] = {}
            for p in partials:
                for gc in p:
                    key = tuple((fr.field, fr.row_id) for fr in gc.group)
                    if key in merged:
                        merged[key].count += gc.count
                    else:
                        merged[key] = GroupCount(group=list(gc.group), count=gc.count)
            out = sorted(merged.values(), key=lambda g: g.compare_key())
            offset = c.uint_arg("offset")
            limit = c.uint_arg("limit")
            if offset:
                out = out[offset:]
            if limit is not None:
                out = out[:limit]
            return out
        raise ExecError(f"no distributed reduce for call {name!r}")

    # ------------------------------------------------------------------
    # call interception
    # ------------------------------------------------------------------

    _FANOUT_CALLS = {
        "Row", "Union", "Intersect", "Difference", "Xor", "Not", "Shift",
        "Range", "All", "Count", "Sum", "Min", "Max", "MinRow", "MaxRow",
        "Rows", "GroupBy", "ClearRow", "Store",
    }

    def _counts_batchable(self, opt: ExecOptions) -> bool:
        # batching evaluates locally over the given shard list, which is
        # only this node's responsibility under remote/single-node
        # execution. Coordinator-side batches are legal exactly when the
        # mesh-group path can fold EVERY call into one sharded dispatch —
        # _execute_count_batch checks per batch and returns None (per-call
        # fan-out) otherwise.
        return opt.remote or self._is_single_node() or self.mesh_min_nodes > 0

    def _execute_call(self, idx: Index, c: Call, shards, opt: ExecOptions):
        if opt.remote or self._is_single_node():
            return super()._execute_call(idx, c, shards, opt)
        name = c.name
        if name in ("Set", "Clear"):
            return self._execute_write_by_column(idx, c)
        if name in ("SetRowAttrs", "SetColumnAttrs"):
            # attrs replicate to every node (reference broadcasts attr writes)
            super()._execute_call(idx, c, shards, ExecOptions(remote=True))
            self._broadcast_call(idx, c)
            return None
        if name == "Options":
            return super()._execute_call(idx, c, shards, opt)
        if name == "TopN":
            return self._execute_topn_distributed(idx, c, shards, opt)
        if name in self._FANOUT_CALLS:
            partials = self._fan_out(
                idx, c, shards, write=name in ("ClearRow", "Store")
            )
            out = self._reduce(name, c, partials)
            if isinstance(out, Row):
                # attrs/exclusions attach on the coordinator only
                # (reference: executeBitmapCall runs the tail on the
                # non-remote node, executor.go:595-647)
                out = self._finish_bitmap_row(idx, c, out, opt)
            return out
        return super()._execute_call(idx, c, shards, opt)

    def _execute_write_by_column(self, idx: Index, c: Call) -> bool:
        """Route a single-column write to every replica owner of its shard
        (executor.go:2142-2172 executeSetBitField)."""
        col = c.args.get("_col")
        if not isinstance(col, int) or isinstance(col, bool):
            raise ExecError(f"{c.name}() column argument required")
        shard = col // SHARD_WIDTH
        cluster = self._cluster()
        owners = cluster.shard_nodes(idx.name, shard)
        changed = False
        errs = []
        failed_nodes = []
        for n in owners:
            try:
                if n.id == self.local_id:
                    r = super()._execute_call(
                        idx, c, [shard], ExecOptions(remote=True)
                    )
                else:
                    r = self.client.query_node(
                        n.uri, idx.name, str(c), shards=[shard], remote=True,
                        # bound the peer-side admission wait: without a
                        # deadline a saturated peer parks this leg's
                        # handler thread indefinitely — long after we
                        # timed out and recorded pending-repair debt
                        timeout=self.query_deadline,
                        deadline=self.query_deadline,
                    )[0]
                changed = changed or bool(r)
            except Exception as e:
                errs.append(f"{n.id}: {e}")
                failed_nodes.append(n)
        if errs and len(errs) == len(owners):
            raise RemoteError("; ".join(errs))
        # partial application: some replica missed this write — visible
        # pending-repair debt instead of silent drift (satellite #2).
        # Only REMOTE replicas at replica_n>1 are recorded: a local-apply
        # failure is not replica drift (the primary's normal AE pushes to
        # us), a self-keyed entry could never be resolved by any sync
        # path, and at replica_n<=1 there is no second copy to repair
        # from so the entry could never drain.
        dropped = [n for n in failed_nodes if n.id != self.local_id]
        if cluster.replica_n > 1:
            for n in dropped:
                self.holder.record_pending_repair(idx.name, shard, n.id)
            if dropped:
                self.stats.count("write_replica_dropped", len(dropped))
        if c.name == "Set":
            self._announce_written_shard(idx, c, shard)
        return changed

    def _announce_written_shard(self, idx: Index, c: Call, shard: int) -> None:
        """Make a newly-created shard visible to cluster-wide fan-out
        (reference: field.AddRemoteAvailableShards broadcast on write)."""
        try:
            field_name = self._field_arg_name(c)
        except ExecError:
            return
        f = idx.field(field_name)
        if f is None:
            return
        # remote_available_shards doubles as "already announced cluster-wide"
        if shard in f.remote_available_shards:
            return
        f.add_remote_available([shard])
        msg = {
            "type": "available-shards",
            "index": idx.name,
            "field": field_name,
            "shards": [shard],
        }

        def send(n):
            try:
                self.client.send_message(n.uri, msg)
            except Exception:
                pass  # peers discover via the next import/announce

        self._to_peers(send)

    def _broadcast_call(self, idx: Index, c: Call) -> None:
        pql = str(c)

        def send(n):
            try:
                self.client.query_node(
                    n.uri, idx.name, pql, shards=None, remote=True,
                    # deadline-bounded so a saturated peer sheds the
                    # broadcast early instead of parking it forever
                    # (drift repairs via anti-entropy either way)
                    timeout=self.query_deadline,
                    deadline=self.query_deadline,
                )
            except Exception:
                pass  # attr drift repairs via anti-entropy

        self._to_peers(send)

    def _to_peers(self, fn) -> None:
        """Run fn(node) for every live peer concurrently — a slow peer must
        not stall a write path (VERDICT r2 weak #3)."""
        peers = [
            n
            for n in self._cluster().nodes
            if n.id != self.local_id and n.state != "DOWN"
        ]
        if not peers:
            return
        if len(peers) == 1:
            fn(peers[0])
            return
        list(self._fanout_pool().map(fn, peers))

    def _topn_fan_out(self, idx: Index, c: Call, shards) -> List[Pair]:
        """One TopN pass across the cluster: partials are untrimmed
        per-node candidate lists with exact per-node counts."""
        partials = self._fan_out(idx, c, shards)
        merged: Dict[int, int] = {}
        for p in partials:
            for pair in p or []:
                merged[pair.id] = merged.get(pair.id, 0) + pair.count
        pairs = [Pair(id=i, count=cnt) for i, cnt in merged.items()]
        pairs.sort(key=lambda p: (-p.count, p.id))
        return pairs

    def _execute_topn_distributed(
        self, idx: Index, c: Call, shards, opt: ExecOptions
    ) -> List[Pair]:
        """Coordinator-level two-pass TopN (executor.go:860-999): pass 1
        collects per-node candidates; pass 2 re-counts the merged candidate
        ids exactly on every node."""
        pairs = self._topn_fan_out(idx, c, shards)
        n = c.uint_arg("n")
        if not pairs or c.args.get("ids"):
            return pairs
        other = Call(c.name, dict(c.args), list(c.children))
        other.args["ids"] = sorted(p.id for p in pairs)
        trimmed = self._topn_fan_out(idx, other, shards)
        if n and len(trimmed) > n:
            trimmed = trimmed[:n]
        return trimmed

    def _shards_for(self, idx: Index, shards, call: Optional[Call] = None) -> List[int]:
        """Cluster-wide shard list: the union of available shards known
        locally plus remote-available bitmaps (field.go:88)."""
        if shards is not None:
            return super()._shards_for(idx, shards, call)
        s = set(idx.available_shards())
        for f in idx.fields(include_hidden=True):
            s.update(f.remote_available_shards)
        base = sorted(s) or [0]
        return super()._shards_for(idx, base, call)
