"""Plane-streamed BSI aggregate execution (the BSI roofline rework).

The pre-existing lowering (`executor._stacked_bsi`) materialized the full
`[bit_depth, S, W]` plane stack before dispatching, so `_chunk_by_budget`
halved the SHARD axis until a depth-wide operand fit the quarter-budget —
a deep int field paid many sequential staged dispatches where Count pays
one — and `sum_counts_stacked`/`min_max_signed` read `[1 + 2D, S]`
partials back for a Python host combine, with kernels that swept the
word rows once per plane (BENCH_NOTES round-10: 5-15x off the Count
roofline at 1B columns).

This module rebuilds the lowering as plane-streamed:

- planes stage and reduce in bounded SLABS of at most `bsi-slab-planes`
  planes (the `[bsi]` knob): each slab is one compiled dispatch whose
  word-local kernels (ops/bsi.py) read every plane word exactly once,
  carrying ladder state between slabs with donated buffers so peak
  plane residency is slab-sized — the shard axis is only chunked when a
  single slab over every shard exceeds the quarter-budget;
- Sum/Min/Max and the single-condition Range/Between counts finish IN
  PROGRAM to scalar-sized halfword-pair results (the plan.py "total"
  contract): under a mesh NamedSharding the final reduction partitions
  into the cross-device collective (psum), so a mesh-group BSI
  aggregate stays exactly 1 dispatch + 1 scalar host read per group;
- dispatches ride `plan.run_counted` so the one-dispatch-per-budget-
  chunk contract is counter-asserted exactly like StackedPlan's.

Fields whose value range cannot store negatives (`options.min >=
options.base` — the bsi_base construction guarantees stored magnitudes
are then non-negative) compile UNSIGNED kernel variants that skip the
sign row entirely: no sign staging, no second popcount branch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from pilosa_tpu.utils.locks import TrackedLock

_DEFAULT_SLAB_PLANES = 16


def _env_slab_planes() -> int:
    raw = os.environ.get("PILOSA_TPU_BSI_SLAB_PLANES")
    try:
        v = int(raw) if raw else _DEFAULT_SLAB_PLANES
    except ValueError:
        return _DEFAULT_SLAB_PLANES
    # same contract as configure(): <= 0 restores the default (a
    # negative slab would make every plane range empty and the
    # aggregates silently zero)
    return v if v > 0 else _DEFAULT_SLAB_PLANES


_slab_planes = _env_slab_planes()

_stats_mu = TrackedLock("bsistream.stats_mu")
_counters: Dict[str, int] = {
    # plane slabs staged+consumed by streamed aggregates (a depth-8
    # field at the default knob is exactly 1 slab per query chunk)
    "slabs": 0,
    # cumulative bytes of plane-slab operands consumed (resident or
    # freshly staged; hbm.restage_bytes books actual uploads)
    "slab_bytes": 0,
    # compiled dispatches issued by the plane-streamed path (slab steps
    # + finishers + degenerate mask counts)
    "plane_dispatches": 0,
}


def configure(slab_planes: Optional[int] = None) -> None:
    """Install the server's [bsi] knobs (cli/config.py -> server/node.py).
    Process-global like the [hbm] knobs — all in-process nodes share one
    device. slab_planes <= 0 restores the default."""
    global _slab_planes
    if slab_planes is not None:
        _slab_planes = int(slab_planes) if slab_planes > 0 else _DEFAULT_SLAB_PLANES


def slab_planes() -> int:
    return _slab_planes


def _bump(key: str, value: int = 1) -> None:
    with _stats_mu:
        _counters[key] += value


def stats_snapshot() -> Dict[str, int]:
    with _stats_mu:
        return dict(_counters)


def reset_stats() -> None:
    with _stats_mu:
        for k in _counters:
            _counters[k] = 0


# ---------------------------------------------------------------------------
# shared staging helpers
# ---------------------------------------------------------------------------


def _quarter_budget() -> int:
    from pilosa_tpu.core.devcache import DEVICE_CACHE

    return max(1, DEVICE_CACHE.budget_bytes // 4)


def _slab_guard(n_shards: int, depth: int) -> None:
    """The slab-peak budget guard: one slab of planes plus the word rows
    (exists, sign, filter) and one generation of carried ladder state
    must fit the quarter-budget; otherwise BudgetExceeded and the caller
    halves the SHARD axis (exec.executor._chunk_by_budget) — the plane
    axis is already slab-bounded, so this fires far later than the old
    bit_depth+3 whole-stack guard."""
    from pilosa_tpu.exec.plan import BudgetExceeded
    from pilosa_tpu.shardwidth import WORDS_PER_ROW

    # exactness bound, independent of the byte budget: the min/max
    # attain count accumulates in uint32 IN PROGRAM, so one chunk may
    # span at most 2048 shards (2^31 columns) — huge-budget configs
    # chunk rather than risk a wrapped count
    if n_shards > 2048:
        raise BudgetExceeded("BSI chunk exceeds the exact-count bound")
    mult = min(max(depth, 1), _slab_planes) + 3
    if n_shards * WORDS_PER_ROW * 4 * mult > _quarter_budget():
        raise BudgetExceeded("BSI slab exceeds device budget")


def _run(fn, read: bool = True):
    from pilosa_tpu.exec import plan as planmod

    _bump("plane_dispatches")
    return planmod.run_counted(fn, read=read)


def _stage_slab(bsiv, lo: int, d: int, shards) -> Any:
    """Stage one plane slab (absolute planes [lo, lo+d)) via the view's
    version-keyed residency path, as the TUPLE of per-extent [d, s_i, W]
    parts — the kernels reduce across parts in program, so the slab is
    never concatenated (a device-side concat would re-copy the whole
    slab on every staging)."""
    from pilosa_tpu.core.fragment import BSI_OFFSET_BIT

    planes = bsiv.plane_stack(
        range(BSI_OFFSET_BIT + lo, BSI_OFFSET_BIT + lo + d), shards,
        parts=True,
    )
    _bump("slabs")
    if planes is not None:
        _bump(
            "slab_bytes",
            sum(int(getattr(p, "nbytes", 0)) for p in planes),
        )
    return planes


def _signed_field(f) -> bool:
    """Whether the field can store negative base-values: bsi_base makes
    stored = value - base, and every write is range-checked against
    [min, max], so min >= base implies an empty sign row forever."""
    return f.options.min < f.options.base


def _field_rows(bsiv, shards, signed_: bool):
    """(exists, sign) word-row PART tuples for one shard chunk; sign is
    None for unsigned fields (the kernels compile sign-free variants).
    Parts align with _stage_slab's: same shard list, same extent rows."""
    from pilosa_tpu.core.fragment import BSI_EXISTS_BIT, BSI_SIGN_BIT

    exists = bsiv.row_stack(BSI_EXISTS_BIT, shards, parts=True)
    if exists is None:
        return None, None
    sign = (
        bsiv.row_stack(BSI_SIGN_BIT, shards, parts=True)
        if signed_
        else None
    )
    return exists, sign


_EMPTY = "empty"  # chunk sentinel: no data -> zero contribution


def _filter_stack(ex, idx, filter_call, shards):
    """Lower an aggregate's filter bitmap to a [S, W] device stack over
    `shards` (mirrors executor._stacked_bsi's filter handling). Returns
    the stack, _EMPTY when the filter matches nothing, or None when the
    filter has no stacked form (caller falls back)."""
    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.exec.executor import _StackedLowering
    from pilosa_tpu.exec.plan import PZero, StackedPlan, Unsupported

    low = _StackedLowering(ex, idx, list(shards), no_sparse_guard=True)
    try:
        with DEVICE_CACHE.deferred_eviction():
            root = low.lower(filter_call)
            if isinstance(root, PZero):
                return _EMPTY
            if not low.operands:
                return None
            sp = StackedPlan(root, low.operands, low.scalars, len(shards))
            return sp.rows_full()
    except Unsupported:
        return None
    finally:
        # pins protect the staging window only; the assembled stack and
        # the aggregate's own operands hold their own device buffers
        low.extents.release()


def _filter_parts(filt, exists_parts):
    """Slice an assembled [S_pad, W] filter stack into parts aligned
    with the staged operand parts (one bounded device slice per part —
    the filter is plan output, so it arrives assembled by nature)."""
    if filt is None:
        return None
    out = []
    off = 0
    for e in exists_parts:
        n = e.shape[0]
        out.append(filt[off:off + n])
        off += n
    return tuple(out)




# ---------------------------------------------------------------------------
# Sum / Min / Max
# ---------------------------------------------------------------------------


def aggregate(ex, idx, c, f, shard_list: Sequence[int], kind: str):
    """Whole-field BSI aggregate (kind in sum|min|max) via the streamed
    lowering. Returns a ValCount, or None to fall back to the legacy
    stacked/per-shard paths (no stacked form for the filter, stream-
    ineligible depth). Raises ExecError for semantic errors exactly like
    the legacy path would."""
    from pilosa_tpu.exec import executor as exmod

    depth = f.options.bit_depth
    signed_ = _signed_field(f)
    if depth <= 0 or depth > 32 or (signed_ and depth > 31):
        # the virtual-key ladder needs depth(+sign) key bits in uint32
        return None
    if not exmod._STACKED_ENABLED or not shard_list:
        return None
    bsiv = f.view(f.bsi_view_name())
    if bsiv is None:
        return exmod.ValCount(0, 0)
    filter_call = None
    if len(c.children) == 1:
        filter_call = c.children[0]
    else:
        fa = c.args.get("filter")
        if fa is not None:
            if not isinstance(fa, exmod.Call):
                return None
            filter_call = fa
    if filter_call is not None and ex._count_shifts(filter_call):
        return None  # Shift needs predecessor-shard augmentation
    bsi_shards = [
        s for s in shard_list if bsiv.fragment_if_exists(s) is not None
    ]
    if not bsi_shards:
        return exmod.ValCount(0, 0)

    def one(chunk):
        # guard BEFORE any staging; a BudgetExceeded from here (or from
        # the filter lowering inside the chunk) halves the shard axis
        _slab_guard(len(chunk), depth)
        part = _aggregate_chunk(
            ex, idx, bsiv, f, filter_call, chunk, kind, depth, signed_
        )
        return None if part is None else [part]

    parts = ex._chunk_by_budget(list(bsi_shards), one)
    if parts is None:
        return None
    count = 0
    total = 0
    best: Optional[Tuple[int, int]] = None  # (value, count) for min/max
    for part in parts:
        if part == _EMPTY:
            continue
        if kind == "sum":
            count += part[0]
            total += part[1]
        else:
            val, cnt, any_ = part
            if not any_ or cnt == 0:
                continue
            if best is None or (
                (val < best[0]) if kind == "min" else (val > best[0])
            ):
                best = (val, cnt)
            elif val == best[0]:
                best = (val, best[1] + cnt)
    if kind == "sum":
        return exmod.ValCount(value=total + count * f.options.base, count=count)
    if best is None:
        return exmod.ValCount(0, 0)
    return exmod.ValCount(value=best[0] + f.options.base, count=best[1])


def _aggregate_chunk(ex, idx, bsiv, f, filter_call, chunk, kind: str,
                     depth: int, signed_: bool):
    """One shard chunk's streamed aggregate: stage word rows + filter
    once, then walk plane slabs. Returns (count, weighted_total) for
    sum, (value, count, any) for min/max, _EMPTY, or None (fallback)."""
    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.exec import plan as planmod
    from pilosa_tpu.ops import bsi as obsi

    with DEVICE_CACHE.deferred_eviction():
        exists, sign = _field_rows(bsiv, chunk, signed_)
        if exists is None:
            return _EMPTY
        filt = None
        if filter_call is not None:
            filt = _filter_stack(ex, idx, filter_call, chunk)
            if filt is None:
                return None
            if filt == _EMPTY:
                return _EMPTY
            filt = _filter_parts(filt, exists)
        slab = _slab_planes
        if kind == "sum":
            # consider computed ONCE per chunk and shared by every slab
            consider = exists
            if filt is not None:
                import jax.numpy as jnp

                consider = planmod.run_serialized(
                    lambda: tuple(
                        jnp.bitwise_and(e, filt[i])
                        for i, e in enumerate(exists)
                    )
                )
            count = 0
            total = 0
            for lo in range(0, depth, slab):
                d = min(slab, depth - lo)
                planes = _stage_slab(bsiv, lo, d, chunk)
                host = np.asarray(
                    _run(
                        lambda planes=planes, lo=lo:
                        obsi.sum_stream_slab(
                            planes, consider, sign, signed_, lo == 0
                        )
                    ),
                    dtype=np.uint64,
                )
                cnt, part = obsi.decode_sum_slab(
                    host, signed_, lo == 0, lo, d
                )
                count += cnt
                total += part
            return count, total
        # min/max
        is_min = kind == "min"
        if depth <= slab:
            planes = _stage_slab(bsiv, 0, depth, chunk)
            host = np.asarray(
                _run(
                    lambda: obsi.min_max_stream(
                        planes, exists, sign, filt, is_min, signed_
                    )
                ),
                dtype=np.uint64,
            )
        else:
            # EMPTY state on the first step — the kernel inits in
            # program. Never pass live arrays as placeholders: the step
            # jit DONATES the state argnums on accelerators, and a
            # donated placeholder that aliases a cached operand (the
            # exists parts) would be deleted under the cache's feet.
            fa: tuple = ()
            va: tuple = ()
            los = list(range(0, depth, slab))
            for n, lo in enumerate(reversed(los)):
                d = min(slab, depth - lo)
                planes = _stage_slab(bsiv, lo, d, chunk)
                fa, va = _run(
                    lambda planes=planes, fa=fa, va=va, n=n:
                    obsi.min_max_stream_step(
                        planes, exists, sign, filt, fa, va,
                        is_min, signed_, n == 0
                    ),
                    read=False,
                )
            host = np.asarray(
                _run(
                    lambda: obsi.min_max_stream_finish(
                        exists, sign, filt, fa, va,
                        depth + (1 if signed_ else 0),
                    )
                ),
                dtype=np.uint64,
            )
    val, cnt, any_ = obsi.decode_min_max(host, depth, is_min, signed_)
    if not any_:
        return _EMPTY
    return val, cnt, any_


# ---------------------------------------------------------------------------
# single-condition Range/Between counts
# ---------------------------------------------------------------------------


def count_range(ex, idx, c, shard_list: Sequence[int]) -> Optional[int]:
    """Count(Row(<single BSI condition>)) via the streamed ladders:
    slab-bounded plane residency, one dispatch per slab (one total at
    depth <= slab), scalar halfword-pair reads. Returns None for shapes
    this path does not own — the caller's plan/per-shard lowering then
    applies its own (identical) semantic checks."""
    from pilosa_tpu.core.field import FIELD_TYPE_INT
    from pilosa_tpu.exec import executor as exmod

    if not exmod._STACKED_ENABLED or not shard_list:
        return None
    conds = c.condition_args()
    if len(c.args) != 1 or len(conds) != 1 or c.children:
        return None
    field_name, cond = next(iter(conds.items()))
    f = idx.field(field_name)
    if f is None or f.options.type != FIELD_TYPE_INT:
        return None  # the legacy path raises the canonical ExecError
    depth = f.options.bit_depth
    if depth <= 0 or depth > 32:
        return None
    signed_ = _signed_field(f)
    bsiv = f.view(f.bsi_view_name())
    if bsiv is None:
        return 0
    dec = _decompose(f, cond, signed_)
    if dec is None:
        return None
    if dec == _ZERO:
        return 0
    jobs, preds, job_weights, extras = dec
    bsi_shards = [
        s for s in shard_list if bsiv.fragment_if_exists(s) is not None
    ]
    if not bsi_shards:
        return 0

    def one(chunk):
        # degenerate NEQ(None)/saturated shapes carry no ladder jobs:
        # they still stream (one mask-count dispatch per chunk), so
        # plane depth only prices the guard when planes are read
        _slab_guard(len(chunk), depth if jobs else 1)
        return [
            _count_chunk(
                bsiv, chunk, depth, signed_, jobs, preds, job_weights,
                extras,
            )
        ]

    parts = ex._chunk_by_budget(list(bsi_shards), one)
    if parts is None:
        return None
    return sum(parts)


# decomposition sentinel: the predicate provably matches nothing
_ZERO = ((), (), (), ())


def _decompose(f, cond, signed_: bool):
    """Mirror of executor._lower_row_bsi's sign/saturation decomposition
    (itself mirroring fragment.range_op/range_between), producing static
    ladder-job descriptors: (jobs, preds, job_weights, extras) where
    jobs = ((kind, mask_sel, allow_eq), ...), preds are uint32
    magnitudes aligned with the jobs (two for between), job_weights and
    extras carry the +/-1 host-combine weights ((sel, weight), ...).
    For unsigned fields the pos/neg selectors collapse: "pos" becomes
    "consider" and "neg" terms drop (the sign row is provably empty).
    Returns None for shapes the streamed path does not own."""
    from pilosa_tpu.pql.ast import BETWEEN, EQ, GT, GTE, LT, LTE, NEQ

    o = f.options

    def final(jobs, preds, weights, extras):
        if signed_:
            return tuple(jobs), tuple(preds), tuple(weights), tuple(extras)
        jobs2, preds2, weights2 = [], [], []
        off = 0
        for job, w in zip(jobs, weights):
            npred = 2 if job[0] == "between" else 1
            if job[1] == "neg":
                off += npred
                continue  # empty mask: zero contribution
            sel = "consider" if job[1] == "pos" else job[1]
            jobs2.append((job[0], sel, job[2]))
            preds2.extend(preds[off:off + npred])
            weights2.append(w)
            off += npred
        extras2 = []
        for sel, w in extras:
            if sel == "neg":
                continue
            extras2.append(("consider" if sel == "pos" else sel, w))
        return tuple(jobs2), tuple(preds2), tuple(weights2), tuple(extras2)

    consider_only = final([], [], [], [("consider", 1)])

    if cond.op == NEQ and cond.value is None:  # != null
        return consider_only
    if cond.op == BETWEEN:
        lo, hi = cond.int_pair()
        blo, bhi, out_of_range = f.base_value_between(lo, hi)
        if out_of_range:
            return _ZERO
        if lo <= o.min and hi >= o.max:
            return consider_only
        if blo >= 0:
            return final(
                [("between", "pos", False)], [abs(blo), abs(bhi)], [1], []
            )
        if bhi < 0:
            return final(
                [("between", "neg", False)], [abs(bhi), abs(blo)], [1], []
            )
        return final(
            [("lt", "pos", True), ("lt", "neg", True)],
            [abs(bhi), abs(blo)], [1, 1], [],
        )

    if not isinstance(cond.value, int) or isinstance(cond.value, bool):
        return None  # the legacy path raises the canonical ExecError
    value = cond.value
    op = {EQ: "eq", NEQ: "neq", LT: "lt", LTE: "lte", GT: "gt", GTE: "gte"}[
        cond.op
    ]
    base_value, out_of_range = f.base_value(op, value)
    if out_of_range and cond.op != NEQ:
        return _ZERO
    if (
        (cond.op == LT and value > o.max)
        or (cond.op == LTE and value >= o.max)
        or (cond.op == GT and value < o.min)
        or (cond.op == GTE and value <= o.min)
    ):
        return consider_only
    if out_of_range and cond.op == NEQ:
        return consider_only
    upred = abs(base_value)
    if op in ("eq", "neq"):
        sel = "neg" if base_value < 0 else "pos"
        if op == "eq":
            return final([("eq", sel, False)], [upred], [1], [])
        return final([("eq", sel, False)], [upred], [-1], [("consider", 1)])
    if op in ("lt", "lte"):
        allow_eq = op == "lte"
        if base_value > 0 or (base_value == 0 and allow_eq):
            return final(
                [("lt", "pos", allow_eq)], [upred], [1], [("neg", 1)]
            )
        if base_value == 0:  # strict < 0
            return final([], [], [], [("neg", 1)])
        return final([("gt", "neg", allow_eq)], [upred], [1], [])
    if op in ("gt", "gte"):
        allow_eq = op == "gte"
        if base_value > 0 or (base_value == 0 and allow_eq):
            return final([("gt", "pos", allow_eq)], [upred], [1], [])
        if base_value == 0:  # strict > 0
            return final([("gt", "pos", False)], [upred], [1], [])
        return final(
            [("lt", "neg", allow_eq)], [upred], [1], [("pos", 1)]
        )
    return None


def _count_chunk(bsiv, chunk, depth: int, signed_: bool, jobs, preds,
                 job_weights, extras) -> int:
    """One shard chunk's streamed range count; exact host combine of the
    per-term halfword pairs with the decomposition's +/- weights."""
    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.ops import bsi as obsi

    import jax.numpy as jnp

    if not jobs and not extras:
        return 0
    with DEVICE_CACHE.deferred_eviction():
        exists, sign = _field_rows(bsiv, chunk, signed_)
        if exists is None:
            return 0
        filt = None  # Count(Row(cond)) carries no separate filter
        upreds = tuple(jnp.uint32(p) for p in preds)
        extra_sels = tuple(sel for sel, _ in extras)
        if not jobs:
            # pure mask count: != null, strict < 0, saturated predicates
            host = np.asarray(
                _run(
                    lambda: obsi.mask_count_pair(
                        exists, sign, filt, extra_sels[0]
                    )
                ),
                dtype=np.uint64,
            )
            return extras[0][1] * obsi.pair_value(host)
        slab = _slab_planes
        if depth <= slab:
            planes = _stage_slab(bsiv, 0, depth, chunk)
            host = np.asarray(
                _run(
                    lambda: obsi.range_stream_single(
                        planes, exists, sign, filt, upreds, jobs, extra_sels
                    )
                ),
                dtype=np.uint64,
            )
        else:
            state: tuple = ()
            los = list(range(0, depth, slab))
            for n, lo in enumerate(reversed(los)):
                d = min(slab, depth - lo)
                planes = _stage_slab(bsiv, lo, d, chunk)
                state = _run(
                    lambda planes=planes, state=state, lo=lo, n=n:
                    obsi.range_stream_step(
                        planes, exists, sign, filt, state, upreds,
                        jobs, lo, n == 0
                    ),
                    read=False,
                )
            host = np.asarray(
                _run(
                    lambda: obsi.range_stream_finish(
                        exists, sign, filt, state, jobs, extra_sels
                    )
                ),
                dtype=np.uint64,
            )
    total = 0
    off = 0
    for w in job_weights:
        total += w * obsi.pair_value(host, off)
        off += 2
    for _, w in extras:
        total += w * obsi.pair_value(host, off)
        off += 2
    return total
