"""Mesh-group lowering: one compiled sharded program over an ICI domain.

The distributed executor (exec/distributed.py) answers a multi-node query
with one HTTP leg per owner node plus a host-side reduce — on tunneled
hardware that is ~RTT x blocking-read-count (BENCH_NOTES round-5). Nodes
that share an ICI domain (cluster/topology.py Node.mesh_group, the [mesh]
knob set) don't need the transport at all: their chips sit on one device
mesh, so their shards can be staged as ONE NamedSharding-placed operand
stack and the whole call tree evaluated as ONE compiled program whose
reduction ends in the collective (exec/plan.py "total" mode) — exactly one
dispatch and one blocking host read regardless of how many nodes or shards
the group spans. HTTP/DCN remains the transport only ACROSS groups,
mirroring the reference's cluster-over-mapReduce split at L2/L3.

Mechanics: a mesh group's members register their holders in the process-
local registry (parallel/mesh.py register_group_member — sharing an ICI
domain means sharing the process's device mesh). This module wraps the
registered holders in Group* adapters that present the group's UNION of
shards as one index to the UNCHANGED single-node lowering
(executor._StackedLowering): GroupView stages a row across the group as
one [S, W] stack (shard -> owning member resolved through the fan-out's
assignment), so Count/Intersect/Union/Difference/Xor/Not trees, BSI
condition rows and the TopN tally all lower exactly as they do on one
node — the mesh IS the executor, now spanning the group.

Staging coexists with the extent path: group stacks ride the same
hbm/residency staging (monolithic under an active mesh — XLA owns
cross-chip layout — extent-paged otherwise) with fragment versions baked
into the cache keys, so a member's write re-keys the covering entry and
the next query re-stages exactly the dirty slice; entries are owned by
per-group tokens and never served stale.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.core.devcache import DEVICE_CACHE, new_owner_token
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.pql.ast import Call
from pilosa_tpu.shardwidth import WORDS_PER_ROW
from pilosa_tpu.utils.locks import TrackedLock


class MeshUnsupported(Exception):
    """The call (or its operands) has no mesh-group form; the caller falls
    back to per-node HTTP legs — never an error surface. `reason` is a
    LOW-CARDINALITY tag (budget / no_stacked_form / unsupported) for the
    `mesh.fallback` counter, so fallback-rate regressions are visible on
    dashboards instead of silent."""

    def __init__(self, msg: str = "", reason: str = "unsupported"):
        super().__init__(msg)
        self.reason = reason


# Calls the mesh-group path may fold into one sharded program. Shift is
# excluded: its cross-shard carry reads predecessor shards that may live
# OUTSIDE the group (per-node execution composes carries locally, which the
# group-spanning stack cannot reproduce for foreign predecessors). Time
# ranges (from/to args) are excluded because time-view discovery walks the
# COORDINATOR's view list, which need not cover views materialized only on
# a peer. Sum/Min/Max fold via the plane-streamed aggregates
# (exec/bsistream.py): the group adapter's plane stacks stage under the
# mesh sharding and the kernels' in-program reductions partition into the
# cross-device psum, so a mesh-group BSI aggregate is one dispatch + one
# scalar host read regardless of group size — the Count "total" contract
# extended to the whole BSI family.
_ELIGIBLE = frozenset(
    {"Count", "Row", "Union", "Intersect", "Difference", "Xor", "Not", "All",
     "TopN", "Sum", "Min", "Max"}
)


def eligible(c: Call) -> bool:
    """True when the whole call tree is foldable into a mesh-group
    dispatch (structure check only — operand shapes may still bail to
    MeshUnsupported at lowering time)."""
    if c.name not in _ELIGIBLE:
        return False
    if "from" in c.args or "to" in c.args:
        return False
    for child in c.children:
        if not eligible(child):
            return False
    for v in c.args.values():
        if isinstance(v, Call) and not eligible(v):
            return False
    return True


# ---------------------------------------------------------------------------
# dispatch accounting (satellite: observability contract). Cumulative
# counters; NodeServer.publish_cache_gauges publishes them as the mesh.*
# gauge families at every scrape/sampler tick.
# ---------------------------------------------------------------------------

_stats_mu = TrackedLock("meshgroup.stats_mu")
_counters: Dict[str, int] = {
    "dispatches": 0,  # mesh-group partials computed
    "local_shards": 0,  # shards served mesh-locally (no HTTP leg, cumulative)
    "collective_bytes": 0,  # bytes moved by in-program collectives (cumulative)
    "fallbacks": 0,  # eligible fan-outs that bailed back to HTTP legs
}


def note_dispatch(group_size: int, n_shards: int, collective_bytes: int) -> None:
    with _stats_mu:
        _counters["dispatches"] += 1
        _counters["local_shards"] += n_shards
        _counters["collective_bytes"] += collective_bytes
    del group_size  # tagged on the span; the gauge reads the live registry


def note_fallback() -> None:
    with _stats_mu:
        _counters["fallbacks"] += 1


def stats_snapshot() -> Dict[str, int]:
    with _stats_mu:
        return dict(_counters)


def reset_stats() -> None:
    with _stats_mu:
        for k in _counters:
            _counters[k] = 0


# ---------------------------------------------------------------------------
# Group adapters: present the group's union of shards as ONE index/field/
# view to the unchanged single-node stacked lowering.
# ---------------------------------------------------------------------------


class GroupView:
    """One (field, view) across the group: the shape _StackedLowering and
    the TopN tally expect of a View, with shard -> owning member resolved
    through the fan-out's assignment. Operand stacks are staged through
    hbm/residency under this view's own owner token, version-keyed per
    shard position exactly like View.row_stack — a member's write re-keys
    the covering entry, so group stacks are never served stale."""

    def __init__(self, gidx: "GroupIndex", view_name: str,
                 member_field: Callable[[object], Optional[object]]):
        self.index = gidx.name
        self.name = view_name
        self._gidx = gidx
        self._member_field = member_field
        self._stack_token = new_owner_token()
        self._view_memo: Dict[str, Optional[object]] = {}

    # -- member resolution --------------------------------------------------

    def _view_of(self, node_id: Optional[str]):
        if node_id is None:
            return None
        v = self._view_memo.get(node_id)
        if v is None:
            # memoize only RESOLVED views: views materialize lazily on a
            # member's first write, and this adapter is cached across
            # queries — a memoized miss would pin the view invisible (and
            # its rows at zero) long after data landed. Re-resolving a
            # miss is three dict lookups; a member-side field recreate
            # also heals through the same re-resolution.
            holder = self._gidx.members.get(node_id)
            idx = holder.index(self._gidx.name) if holder is not None else None
            f = self._member_field(idx) if idx is not None else None
            v = f.view(self.name) if f is not None else None
            if v is not None:
                self._view_memo[node_id] = v
        return v

    def _owner_view(self, shard: int):
        return self._view_of(self._gidx.assignment.get(shard))

    # -- the View surface the lowering and tally paths use ------------------

    def fragment_if_exists(self, shard: int):
        v = self._owner_view(shard)
        return v.fragment_if_exists(shard) if v is not None else None

    def _frags_for(self, shards: Tuple[int, ...]):
        """(frags by position, member view -> its frags) for one stack."""
        frags = []
        by_view: Dict[int, Tuple[object, List[object]]] = {}
        for s in shards:
            v = self._owner_view(s)
            frag = v.fragment_if_exists(s) if v is not None else None
            frags.append(frag)
            if v is not None and frag is not None:
                by_view.setdefault(id(v), (v, []))[1].append(frag)
        return frags, by_view

    def sync_pending(self, shards=None, frags=None) -> None:
        """Read barrier across the group: each member view merges its own
        staged burst (core/merge.py batches per member — no fragment lock
        is ever held across another member's)."""
        if frags is None:
            if shards is None:
                return
            frags = [self.fragment_if_exists(s) for s in shards]
        by_view: Dict[int, Tuple[object, List[object]]] = {}
        for frag in frags:
            if frag is None:
                continue
            v = self._owner_view(frag.shard)
            if v is not None:
                by_view.setdefault(id(v), (v, []))[1].append(frag)
        for v, fl in by_view.values():
            v.sync_pending(frags=fl)

    def _base_key(self, kind: str, ident, shards: tuple) -> tuple:
        # same shape as View._stack_key so downstream key handling (extent
        # spans, version slices) parses identically; staging appends the
        # per-extent version slices itself
        return (self._stack_token, kind, ident, shards, pmesh.mesh_epoch())

    def _stack_key(self, kind: str, ident, shards: tuple) -> tuple:
        """Version-salted key for EXTERNAL cachers (the TopN tally
        bundle). Nothing eagerly invalidates group-token entries — a
        member fragment's on_mutate only fires on its OWN view's token —
        so correctness rests entirely on the versions baked in here: a
        member write re-keys the entry and the stale one ages out via
        LRU, exactly like the staged stacks' version slices."""
        shards = tuple(shards)
        frags, _ = self._frags_for(shards)
        versions = tuple(f.version if f is not None else -1 for f in frags)
        return self._base_key(kind, ident, shards) + (versions,)

    def row_stack(self, row_id: int, shards, extents=None,
                  parts: bool = False):
        """uint32[S, W] device stack of one row over the GROUP's shards
        (None when wholly absent) — the group-spanning analog of
        View.row_stack, staged under this adapter's owner token."""
        from pilosa_tpu.hbm import residency as hbm_res

        shards = tuple(shards)
        frags, by_view = self._frags_for(shards)
        if all(f is None for f in frags):
            return None
        for v, fl in by_view.values():
            v.sync_pending(frags=fl)
        versions = tuple(f.version if f is not None else -1 for f in frags)
        key = self._base_key("row", row_id, shards)

        def build_slice(lo: int, hi: int):
            zeros = np.zeros(WORDS_PER_ROW, np.uint32)
            return np.stack(
                [
                    f.row_words(row_id) if f is not None else zeros
                    for f in frags[lo:hi]
                ]
            )

        return hbm_res.stage_row_stack(
            key, len(shards), build_slice, table=extents,
            versions=versions, shards=shards, index=self.index,
            parts=parts,
        )

    def plane_stack(self, row_ids, shards, extents=None,
                    parts: bool = False):
        """uint32[D, S, W] BSI plane stack over the group's shards."""
        from pilosa_tpu.hbm import residency as hbm_res

        row_ids = tuple(row_ids)
        shards = tuple(shards)
        frags, by_view = self._frags_for(shards)
        if all(f is None for f in frags):
            return None
        for v, fl in by_view.values():
            v.sync_pending(frags=fl)
        versions = tuple(f.version if f is not None else -1 for f in frags)
        key = self._base_key("planes", row_ids, shards)

        def build_slice(lo: int, hi: int):
            part = frags[lo:hi]
            if not row_ids:
                return np.zeros((0, len(part), WORDS_PER_ROW), np.uint32)
            zeros = np.zeros(WORDS_PER_ROW, np.uint32)
            return np.stack(
                [
                    np.stack(
                        [
                            f.row_words(r) if f is not None else zeros
                            for f in part
                        ]
                    )
                    for r in row_ids
                ]
            )

        return hbm_res.stage_plane_stack(
            key, len(shards), build_slice, table=extents,
            versions=versions, shards=shards, index=self.index,
            parts=parts,
        )

    def close(self) -> None:
        DEVICE_CACHE.invalidate_owner(self._stack_token)


class GroupField:
    """Field adapter: schema/metadata (options, BSI base math, row attrs —
    all replicated cluster-wide) comes from the coordinator's field; DATA
    access goes through GroupViews spanning the members."""

    def __init__(self, gidx: "GroupIndex", coord_field,
                 member_field: Callable[[object], Optional[object]]):
        self._gidx = gidx
        self._f = coord_field
        self._member_field = member_field
        self.name = coord_field.name
        self._views: Dict[str, GroupView] = {}

    @property
    def options(self):
        return self._f.options

    @property
    def row_attr_store(self):
        return self._f.row_attr_store

    @property
    def views(self):
        # metadata-only surface (time-view discovery); time ranges are
        # gated out of the mesh path, so the coordinator's list suffices
        return self._f.views

    def bsi_view_name(self) -> str:
        return self._f.bsi_view_name()

    def base_value(self, *a, **kw):
        return self._f.base_value(*a, **kw)

    def base_value_between(self, *a, **kw):
        return self._f.base_value_between(*a, **kw)

    def view(self, name: str) -> Optional[GroupView]:
        gv = self._views.get(name)
        if gv is None:
            # a view absent EVERYWHERE lowers to PZero via the adapter's
            # empty fragment map, matching the serial path's None view;
            # constructing it lazily is still cheap (no fragment access)
            gv = self._views[name] = GroupView(
                self._gidx, name, self._member_field
            )
        return gv

    def close(self) -> None:
        for gv in self._views.values():
            gv.close()


class GroupIndex:
    """Index adapter handed to the unchanged single-node lowering: schema
    from the coordinator's index, shard data resolved across the group's
    registered holders by the fan-out's shard -> node assignment."""

    def __init__(self, coord_index, members: Dict[str, object],
                 assignment: Dict[int, str]):
        self.name = coord_index.name
        self._idx = coord_index
        self.members = members
        self.assignment = assignment
        self._fields: Dict[str, GroupField] = {}

    @property
    def keys(self):
        return self._idx.keys

    @property
    def track_existence(self):
        return self._idx.track_existence

    def field(self, name: str) -> Optional[GroupField]:
        gf = self._fields.get(name)
        if gf is None:
            f = self._idx.field(name)
            if f is None:
                return None
            gf = self._fields[name] = GroupField(
                self, f, lambda idx, n=name: idx.field(n)
            )
        return gf

    def existence_field(self) -> Optional[GroupField]:
        ef = self._idx.existence_field()
        if ef is None:
            return None
        gf = self._fields.get(ef.name)
        if gf is None:
            gf = self._fields[ef.name] = GroupField(
                self, ef, lambda idx: idx.existence_field()
            )
        return gf

    def available_shards(self) -> List[int]:
        return sorted(self.assignment)

    def close(self) -> None:
        for gf in self._fields.values():
            gf.close()


# ---------------------------------------------------------------------------
# GroupIndex cache: device-cache reuse across queries requires stable owner
# tokens, so adapters persist per (coordinator index, assignment,
# membership generation). Bounded LRU; evicted adapters invalidate their
# tokens' device entries (version-keyed — never stale — but dead weight).
# ---------------------------------------------------------------------------

_CACHE_MAX = 8
_cache_mu = TrackedLock("meshgroup.cache_mu")
_cache: "OrderedDict[tuple, GroupIndex]" = OrderedDict()


def group_index(coord_index, members: Dict[str, object],
                assignment_by_node: Dict[str, List[int]]) -> GroupIndex:
    """Get-or-build the adapter for one (index, shard assignment,
    membership) combination. The registry generation in the key makes a
    restarted member's stale holder unreachable through a cached adapter."""
    assignment: Dict[int, str] = {}
    for nid, shards in assignment_by_node.items():
        for s in shards:
            assignment[s] = nid
    key = (
        coord_index.name,
        id(coord_index),
        tuple(sorted((nid, tuple(sorted(sh)))
                     for nid, sh in assignment_by_node.items())),
        pmesh.group_generation(),
    )
    with _cache_mu:
        gi = _cache.get(key)
        if gi is not None:
            _cache.move_to_end(key)
            return gi
    gi = GroupIndex(coord_index, dict(members), assignment)
    evicted = []
    with _cache_mu:
        cur = _cache.get(key)
        if cur is not None:
            gi = cur
        else:
            _cache[key] = gi
            while len(_cache) > _CACHE_MAX:
                evicted.append(_cache.popitem(last=False)[1])
    for old in evicted:
        old.close()
    return gi


def drop_index(index_name: str) -> None:
    """GC hook (NodeServer.drop_index_telemetry): a deleted index's group
    adapters — and their device-cache entries — must not outlive it."""
    dead = []
    with _cache_mu:
        for key in [k for k in _cache if k[0] == index_name]:
            dead.append(_cache.pop(key))
    for gi in dead:
        gi.close()


def clear_cache() -> None:
    with _cache_mu:
        dead = list(_cache.values())
        _cache.clear()
    for gi in dead:
        gi.close()


# ---------------------------------------------------------------------------
# mesh-group dispatch helpers (called by exec/distributed.py)
# ---------------------------------------------------------------------------


def mesh_count(ex, gidx: GroupIndex, c: Call, shard_list: List[int]) -> Tuple[int, int]:
    """Count(<bitmap tree>) over the group as ONE compiled program ending
    in the in-program reduction (plan "total" mode): one dispatch + one
    scalar-sized blocking read however many shards the group holds.
    Returns (total, collective_bytes). Raises MeshUnsupported when the
    child has no stacked form or the operands exceed the device budget
    (per-node legs chunk within their own budgets instead)."""
    from pilosa_tpu.exec.plan import BudgetExceeded, StackedPlan

    if len(c.children) != 1:
        from pilosa_tpu.exec.executor import ExecError

        raise ExecError("Count() only accepts a single bitmap input")
    child = c.children[0]
    if child.name in ("Row", "Range") and child.has_conditions():
        # single-BSI-condition counts ride the plane-streamed ladders
        # over the group adapter (exec/bsistream.py): the in-program
        # halfword-pair reductions partition into the mesh psum, so the
        # group answers in one dispatch per slab with a scalar read
        from pilosa_tpu.exec import bsistream

        streamed = bsistream.count_range(ex, gidx, child, shard_list)
        if streamed is not None:
            return streamed, 4 * 4  # two halfword pairs replicated
    try:
        lowered = ex._lower_roots(gidx, [child], shard_list, empty_ok=True)
    except BudgetExceeded as e:
        raise MeshUnsupported(str(e), reason="budget") from e
    if lowered is None:
        raise MeshUnsupported("no stacked form", reason="no_stacked_form")
    if lowered == ex._EMPTY_LOWER:
        return 0, 0
    roots, low, n_out, out_shards = lowered
    sp = StackedPlan(
        roots[0], low.operands, low.scalars, n_out, out_shards,
        extents=low.extents,
    )
    # collective payload: the [S]-per-shard partial counts folded across
    # devices plus the replicated (lo, hi) result — shard-count-bound,
    # NOT operand-bound (operands never leave their chips)
    return sp.total(), (n_out + 2) * 4


def mesh_count_batch(ex, gidx: GroupIndex, calls: List[Call],
                     shard_list: List[int]) -> Tuple[List[int], int]:
    """N Counts over the group as ONE multi-root compiled program with
    in-program totals (the batcher's mesh lowering class rides this).
    Returns (totals, collective_bytes); MeshUnsupported falls back to
    per-call fan-out."""
    from pilosa_tpu.exec.executor import ExecError
    from pilosa_tpu.exec.plan import BudgetExceeded, MultiCountPlan

    children = []
    for c in calls:
        if len(c.children) != 1:
            raise ExecError("Count() only accepts a single bitmap input")
        children.append(c.children[0])
    try:
        lowered = ex._lower_roots(gidx, children, shard_list, empty_ok=True)
    except BudgetExceeded as e:
        raise MeshUnsupported(str(e), reason="budget") from e
    if lowered is None:
        raise MeshUnsupported("no stacked form", reason="no_stacked_form")
    if lowered == ex._EMPTY_LOWER:
        return [0] * len(calls), 0
    roots, low, n_out, out_shards = lowered
    mp = MultiCountPlan(
        roots, low.operands, low.scalars, n_out, out_shards,
        extents=low.extents,
    )
    return mp.totals(), (n_out + 2) * 4 * len(calls)
