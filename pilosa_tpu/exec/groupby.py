"""Device GroupBy: batched cross-product tally over stacked row operands.

TPU-native replacement for the reference's groupByIterator
(/root/reference/executor.go:3063), which walks the rows cross-product one
group element at a time — in the round-1 rebuild that meant one device
dispatch + host sync per (group-prefix, depth). Here the tally is
level-wise and batched: at depth d, ONE jitted call computes
popcount(acc[g] & planes[r]) for every live prefix g and every candidate
row r across all shards at once, and one host read prunes zero groups
before descending. Dispatch count is O(depth x chunks), independent of the
number of groups.

Shapes: `planes` stacks are uint32[R, S, W] (candidate rows x shards x
words, built by View.plane_stack and shard-axis-sharded under an active
mesh); the accumulator `acc` is uint32[G, S, W] for the G live prefixes.
Counts are reduced over W on device in uint32 (one shard holds at most
2^20 bits, so a per-shard count can never wrap) and over the shard axis
on the host in exact uint64 — the same overflow discipline as
StackedPlan.count (exec/plan.py). The [G, R, S] host transfer stays small
because the prefix tile G shrinks as S grows (G*S*W*4 <= tile bytes).

Memory is bounded by processing prefixes depth-first in chunks of at most
`_gmax()` rows (PILOSA_TPU_GROUPBY_TILE_MB, default 256 MB per tile), so
live device memory is <= depth * tile regardless of group fan-out. Chunk
index vectors are padded to powers of two to bound recompilation.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Dispatch accounting (tests assert O(depth), not O(groups), dispatches).
STATS = {"evals": 0}


def reset_stats() -> None:
    STATS["evals"] = 0


def _tile_bytes() -> int:
    mb = int(os.environ.get("PILOSA_TPU_GROUPBY_TILE_MB", "256"))
    return max(1, mb) << 20


def _gmax(s: int, w: int) -> int:
    return max(1, _tile_bytes() // (s * w * 4))


def _pad_pow2(idx: np.ndarray) -> np.ndarray:
    n = len(idx)
    target = 1 << max(n - 1, 0).bit_length()
    if target == n:
        return idx
    return np.concatenate([idx, np.zeros(target - n, idx.dtype)])


@jax.jit
def _counts_planes(planes):
    """uint32[R, S, W] -> per-shard counts uint32[R, S]."""
    return jnp.sum(jax.lax.population_count(planes), axis=-1, dtype=jnp.uint32)


@jax.jit
def _counts_cross(acc, planes):
    """acc uint32[G, S, W] x planes uint32[R, S, W] -> per-shard counts
    uint32[G, R, S].

    lax.map over the candidate-row axis keeps the live intermediate at
    [G, S, W] instead of materializing the full [G, R, S, W] cross."""

    def per_row(p):
        return jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(acc, p[None])),
            axis=-1,
            dtype=jnp.uint32,
        )

    out = jax.lax.map(per_row, planes)  # [R, G, S]
    return jnp.transpose(out, (1, 0, 2))


def _host_sum(counts) -> np.ndarray:
    """Sum per-shard uint32 counts over the shard axis in exact uint64."""
    return np.asarray(counts).astype(np.uint64).sum(axis=-1)


@jax.jit
def _select_rows(planes, r_idx):
    return planes[r_idx]


@jax.jit
def _select_rows_filtered(planes, r_idx, filt):
    return jnp.bitwise_and(planes[r_idx], filt[None])


@jax.jit
def _select_pairs(acc, planes, g_idx, r_idx):
    return jnp.bitwise_and(acc[g_idx], planes[r_idx])


@jax.jit
def _cross_expand(acc, planes):
    """uint32[G, S, W] x uint32[R, S, W] -> uint32[G*R, S, W], row-major
    (group g, row r) -> g*R + r."""
    out = jnp.bitwise_and(acc[:, None], planes[None])
    return out.reshape(-1, acc.shape[1], acc.shape[2])


# Cap on the fused [G, R_last, S] count read of the one-shot path.
_ONESHOT_READ_BYTES = 64 << 20


# dispatch-ok escapes below: the CALLER holds the mutex —
# executor._group_by_stacked wraps the whole cross-tally pipeline in
# plan.dispatch_mutex() (operands staged before entry)
def group_by_device(  # dispatch-ok: caller holds dispatch_mutex
    planes_list: Sequence[jax.Array],
    row_lists: Sequence[Sequence[int]],
    filt: Optional[jax.Array] = None,
) -> Dict[Tuple[int, ...], int]:
    """Tally the full GroupBy cross-product on device.

    planes_list[k] is the uint32[R_k, S, W] stack of child k's candidate
    rows; row_lists[k] the matching row ids; filt an optional uint32[S, W]
    filter stack (same shard padding). Returns {(row0, row1, ...): count}
    with zero-count groups pruned — the same contract as the per-shard
    groupByIterator walk, summed over all shards."""
    merged: Dict[Tuple[int, ...], int] = {}
    if not planes_list or any(p.shape[0] == 0 for p in planes_list):
        return merged
    depth_n = len(planes_list)
    s, w = planes_list[0].shape[-2], planes_list[0].shape[-1]
    gmax = _gmax(s, w)

    # One-shot path for small cross-products: build the full prefix
    # accumulator on device with NO intermediate host reads, tally the
    # last level, read ONCE. The pruned descent below costs one blocking
    # read per depth — on tunneled hardware that is ~RTT x depth of pure
    # latency — and pruning only pays when the cross-product is too big
    # to materialize anyway.
    g_pre = 1
    for p in planes_list[:-1]:
        g_pre *= int(p.shape[0])
    read_cells = g_pre * int(planes_list[-1].shape[0]) * s * 4
    if g_pre <= gmax and read_cells <= _ONESHOT_READ_BYTES:
        return _group_by_oneshot(planes_list, row_lists, filt)

    # Depth 0: counts for every candidate row of the first child.
    if filt is not None:
        h = _host_sum(_counts_cross(filt[None], planes_list[0])[0])
    else:
        h = _host_sum(_counts_planes(planes_list[0]))
    STATS["evals"] += 1
    live = np.nonzero(h)[0]
    if depth_n == 1:
        for i in live:
            merged[(int(row_lists[0][i]),)] = int(h[i])
        return merged

    for start in range(0, len(live), gmax):
        idx = live[start : start + gmax]
        idx_p = _pad_pow2(idx)
        if filt is not None:
            acc = _select_rows_filtered(planes_list[0], idx_p, filt)
        else:
            acc = _select_rows(planes_list[0], idx_p)
        STATS["evals"] += 1
        prefixes = [(int(row_lists[0][i]),) for i in idx]
        _descend(1, acc, prefixes, planes_list, row_lists, merged, gmax)
    return merged


def _group_by_oneshot(  # dispatch-ok: caller holds dispatch_mutex
    planes_list: Sequence[jax.Array],
    row_lists: Sequence[Sequence[int]],
    filt: Optional[jax.Array],
) -> Dict[Tuple[int, ...], int]:
    """Whole cross-product in one fused device pipeline + ONE host read.
    Zero-count groups are pruned at merge (same contract as the descent).
    All dispatches are async; only the final np.asarray blocks."""
    merged: Dict[Tuple[int, ...], int] = {}
    acc = planes_list[0]
    if filt is not None:
        acc = _select_rows_filtered(acc, np.arange(acc.shape[0]), filt)
        STATS["evals"] += 1
    keys: List[Tuple[int, ...]] = [(int(r),) for r in row_lists[0]]
    for d in range(1, len(planes_list) - 1):
        acc = _cross_expand(acc, planes_list[d])
        STATS["evals"] += 1
        keys = [k + (int(r),) for k in keys for r in row_lists[d]]
    if len(planes_list) == 1:
        h = _host_sum(_counts_planes(acc))
        STATS["evals"] += 1
        for i, cnt in enumerate(h):
            if cnt:
                merged[keys[i]] = int(cnt)
        return merged
    last_rows = row_lists[-1]
    h = _host_sum(_counts_cross(acc, planes_list[-1]))  # [G, R_last]
    STATS["evals"] += 1
    gs, rs = np.nonzero(h)
    for g, r in zip(gs, rs):
        merged[keys[g] + (int(last_rows[r]),)] = int(h[g, r])
    return merged


def _descend(  # dispatch-ok: caller holds dispatch_mutex
    depth: int,
    acc: jax.Array,
    prefixes: List[Tuple[int, ...]],
    planes_list: Sequence[jax.Array],
    row_lists: Sequence[Sequence[int]],
    merged: Dict[Tuple[int, ...], int],
    gmax: int,
) -> None:
    h = _host_sum(_counts_cross(acc, planes_list[depth]))[: len(prefixes)]
    STATS["evals"] += 1
    gs, rs = np.nonzero(h)
    if depth == len(planes_list) - 1:
        for g, r in zip(gs, rs):
            key = prefixes[g] + (int(row_lists[depth][r]),)
            merged[key] = merged.get(key, 0) + int(h[g, r])
        return
    for start in range(0, len(gs), gmax):
        gi = gs[start : start + gmax]
        ri = rs[start : start + gmax]
        acc2 = _select_pairs(
            acc, planes_list[depth], _pad_pow2(gi), _pad_pow2(ri)
        )
        STATS["evals"] += 1
        pfx = [
            prefixes[g] + (int(row_lists[depth][r]),) for g, r in zip(gi, ri)
        ]
        _descend(depth + 1, acc2, pfx, planes_list, row_lists, merged, gmax)
