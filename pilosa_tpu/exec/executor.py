"""Query executor: per-call dispatch + per-shard map + reduce.

Reference: /root/reference/executor.go — executeCall dispatch (:274-339),
per-shard mapReduce (:2460-2613), per-call implementations (:360-2418).

TPU-first structure: every bitmap call lowers, per shard, to dense device
words; cross-child algebra happens on device; cross-shard reduction happens
with exact host ints (counts) or segment maps (rows). The single-node
executor walks shards in a Python loop — the mesh path (parallel/) stacks
shards into one [n_shards, W] sharded array and jits the whole map+reduce
with collectives; both share the per-shard lowering here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pilosa_tpu.core import timeq
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_TIME,
    Field,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec import translation
from pilosa_tpu.ops import bitmap as ob
from pilosa_tpu.pql import Call, Query, parse
from pilosa_tpu.pql.ast import BETWEEN, EQ, GT, GTE, LT, LTE, NEQ
from pilosa_tpu.shardwidth import SHARD_WIDTH

DEFAULT_MIN_THRESHOLD = 1  # reference: defaultMinThreshold, executor.go


class ExecError(Exception):
    pass


class NotFoundError(ExecError):
    pass


@dataclass
class ExecOptions:
    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    column_attrs: bool = False
    shards: Optional[List[int]] = None
    max_writes: int = 5000  # reference: MaxWritesPerRequest


@dataclass
class Pair:
    """TopN result entry (reference: Pair, cache.go:317)."""

    id: int
    count: int
    key: Optional[str] = None

    def to_json(self):
        d = {"id": self.id, "count": self.count}
        if self.key is not None:
            d["key"] = self.key
        return d


@dataclass
class ValCount:
    """Sum/Min/Max result (reference: ValCount, executor.go)."""

    value: int
    count: int

    def to_json(self):
        return {"value": self.value, "count": self.count}


@dataclass
class FieldRow:
    field: str
    row_id: int
    row_key: Optional[str] = None

    def to_json(self):
        if self.row_key:
            return {"field": self.field, "rowKey": self.row_key}
        return {"field": self.field, "rowID": self.row_id}


@dataclass
class GroupCount:
    group: List[FieldRow]
    count: int

    def to_json(self):
        return {"group": [g.to_json() for g in self.group], "count": self.count}

    def compare_key(self):
        return tuple(g.row_id for g in self.group)


_COND_OP_NAME = {EQ: "eq", NEQ: "neq", LT: "lt", LTE: "lte", GT: "gt", GTE: "gte"}


class Executor:
    """Single-node executor. Cluster fan-out wraps this via the same
    per-shard lowering (reference: executor.go:44)."""

    def __init__(self, holder: Holder):
        self.holder = holder

    # ------------------------------------------------------------------
    # entry
    # ------------------------------------------------------------------

    def execute(
        self,
        index_name: str,
        query: Union[str, Query],
        shards: Optional[Sequence[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> List[Any]:
        opt = opt or ExecOptions()
        if isinstance(query, str):
            query = parse(query)
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if query.write_call_n() > opt.max_writes:
            raise ExecError("too many writes in a single request")
        if shards is None:
            shards = opt.shards
        # key -> id translation (executor.go:2615 translateCalls); remote
        # (fan-out) requests arrive pre-translated by the coordinator.
        if not opt.remote:
            translation.translate_query(idx, query)
        results = []
        for call in query.calls:
            results.append(self._execute_call(idx, call, shards, opt))
        # id -> key translation of results (executor.go:2786)
        if not opt.remote:
            results = translation.translate_results(idx, query, results)
        return results

    def _shards_for(self, idx: Index, shards, call: Optional[Call] = None) -> List[int]:
        if shards is not None:
            s = list(shards)
        else:
            s = sorted(idx.available_shards()) or [0]
        if call is not None:
            # Shift carries bits into following shards; materialize them even
            # when the index has no data there yet.
            k = self._count_shifts(call)
            if k:
                ext = set(s)
                for sh in s:
                    ext.update(range(sh + 1, sh + 1 + k))
                s = sorted(ext)
        return s

    # ------------------------------------------------------------------
    # dispatch (executor.go:274)
    # ------------------------------------------------------------------

    def _execute_call(self, idx: Index, c: Call, shards, opt: ExecOptions):
        name = c.name
        if name not in ("Set", "Clear", "SetRowAttrs", "SetColumnAttrs", "Options"):
            shards = self._shards_for(idx, shards, c)
        if name == "Sum":
            return self._execute_sum(idx, c, shards)
        if name == "Min":
            return self._execute_min_max(idx, c, shards, is_min=True)
        if name == "Max":
            return self._execute_min_max(idx, c, shards, is_min=False)
        if name == "MinRow":
            return self._execute_min_max_row(idx, c, shards, is_min=True)
        if name == "MaxRow":
            return self._execute_min_max_row(idx, c, shards, is_min=False)
        if name == "Clear":
            return self._execute_clear(idx, c)
        if name == "ClearRow":
            return self._execute_clear_row(idx, c, shards)
        if name == "Store":
            return self._execute_store(idx, c, shards)
        if name == "Count":
            return self._execute_count(idx, c, shards)
        if name == "Set":
            return self._execute_set(idx, c)
        if name == "SetRowAttrs":
            self._execute_set_row_attrs(idx, c)
            return None
        if name == "SetColumnAttrs":
            self._execute_set_column_attrs(idx, c)
            return None
        if name == "TopN":
            return self._execute_topn(idx, c, shards, opt)
        if name == "Rows":
            return self._execute_rows(idx, c, shards)
        if name == "GroupBy":
            return self._execute_group_by(idx, c, shards)
        if name == "Options":
            return self._execute_options(idx, c, shards, opt)
        return self._execute_bitmap_call(idx, c, shards)

    # ------------------------------------------------------------------
    # bitmap calls
    # ------------------------------------------------------------------

    def _count_shifts(self, c: Call) -> int:
        n = 1 if c.name == "Shift" else 0
        n += sum(self._count_shifts(ch) for ch in c.children)
        n += sum(self._count_shifts(v) for v in c.args.values() if isinstance(v, Call))
        return n

    def _execute_bitmap_call(self, idx: Index, c: Call, shards) -> Row:
        shard_list = self._shards_for(idx, shards)
        segments = {}
        memo: dict = {}
        for shard in shard_list:
            words = self._bitmap_call_shard(idx, c, shard, memo)
            if words is not None:
                segments[shard] = words
        return Row(segments)

    def _bitmap_call_shard(self, idx: Index, c: Call, shard: int, memo=None):
        """Lower one bitmap call for one shard to device words (or None).

        `memo` caches (call, shard) -> words within one query execution so a
        call subtree referenced twice (e.g. by Shift's cross-shard carry) is
        lowered once."""
        if memo is not None:
            key = (id(c), shard)
            if key in memo:
                return memo[key]
        words = self._bitmap_call_shard_uncached(idx, c, shard, memo)
        if memo is not None:
            memo[(id(c), shard)] = words
        return words

    def _bitmap_call_shard_uncached(self, idx: Index, c: Call, shard: int, memo=None):
        name = c.name
        if name in ("Row", "Range"):
            return self._row_shard(idx, c, shard)
        if name == "Intersect":
            return self._nary_shard(idx, c, shard, "intersect", memo)
        if name == "Union":
            return self._nary_shard(idx, c, shard, "union", memo)
        if name == "Difference":
            return self._nary_shard(idx, c, shard, "difference", memo)
        if name == "Xor":
            return self._nary_shard(idx, c, shard, "xor", memo)
        if name == "Not":
            return self._not_shard(idx, c, shard, memo)
        if name == "Shift":
            # Shift crosses shard boundaries: this shard's result is its own
            # child bits shifted up, OR'd with the overflow carried out of the
            # previous shard's child bits — composable per shard, so Shift
            # works nested inside any other call.
            if len(c.children) != 1:
                raise ExecError("Shift() requires a single bitmap input")
            n = c.int_arg("n")
            n = 1 if n is None else n
            cur = self._bitmap_call_shard(idx, c.children[0], shard, memo)
            out = None
            if cur is not None:
                out, _ = ob.shift_bits(cur, n)
            if shard > 0:
                prev = self._bitmap_call_shard(idx, c.children[0], shard - 1, memo)
                if prev is not None:
                    _, carry = ob.shift_bits(prev, n)
                    out = carry if out is None else ob.b_or(out, carry)
            return out
        if name == "All":
            return self._existence_words(idx, shard)
        raise ExecError(f"unknown call: {name}")

    def _nary_shard(self, idx: Index, c: Call, shard: int, op: str, memo=None):
        if not c.children:
            if op == "intersect":
                raise ExecError("empty Intersect query is currently not supported")
            return None
        words = [self._bitmap_call_shard(idx, ch, shard, memo) for ch in c.children]
        zero = None
        if op == "intersect":
            if any(w is None for w in words):
                return None
            out = words[0]
            for w in words[1:]:
                out = ob.b_and(out, w)
            return out
        if op == "union":
            present = [w for w in words if w is not None]
            if not present:
                return None
            out = present[0]
            for w in present[1:]:
                out = ob.b_or(out, w)
            return out
        if op == "difference":
            out = words[0]
            if out is None:
                return None
            for w in words[1:]:
                if w is not None:
                    out = ob.b_andnot(out, w)
            return out
        if op == "xor":
            present = [w for w in words if w is not None]
            if not present:
                return None
            out = present[0]
            for w in present[1:]:
                out = ob.b_xor(out, w)
            return out
        raise AssertionError(op)

    def _not_shard(self, idx: Index, c: Call, shard: int, memo=None):
        """Not via the existence field (executor.go:1734 executeNot)."""
        if not idx.track_existence:
            raise ExecError("Not() query requires existence tracking to be enabled")
        if len(c.children) != 1:
            raise ExecError("Not() requires a single bitmap input")
        exists = self._existence_words(idx, shard)
        if exists is None:
            return None
        child = self._bitmap_call_shard(idx, c.children[0], shard, memo)
        if child is None:
            return exists
        return ob.b_andnot(exists, child)

    def _existence_words(self, idx: Index, shard: int):
        ef = idx.existence_field()
        if ef is None:
            raise ExecError("existence field not available")
        v = ef.view(VIEW_STANDARD)
        if v is None:
            return None
        frag = v.fragment_if_exists(shard)
        return None if frag is None else frag.row_device(0)

    # -- Row / Range -------------------------------------------------------

    def _field_of(self, idx: Index, name: str) -> Field:
        f = idx.field(name)
        if f is None:
            raise NotFoundError(f"field not found: {name}")
        return f

    def _row_shard(self, idx: Index, c: Call, shard: int):
        if c.has_conditions():
            return self._row_bsi_shard(idx, c, shard)
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        row_id = c.args.get(field_name)
        if isinstance(row_id, bool):
            if f.options.type != FIELD_TYPE_BOOL:
                raise ExecError("Row() bool value requires a bool field")
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            if isinstance(row_id, str):
                raise ExecError(
                    f"string row key {row_id!r} requires field keys (translation)"
                )
            raise ExecError("Row() must specify a row")
        if f.options.type == FIELD_TYPE_BOOL and row_id not in (0, 1):
            raise ExecError("Row() bool field expects row 0 or 1")

        from_arg = c.args.get("from")
        to_arg = c.args.get("to")
        if from_arg is None and to_arg is None:
            v = f.view(VIEW_STANDARD)
            if v is None:
                return None
            frag = v.fragment_if_exists(shard)
            return None if frag is None else frag.row_device(row_id)

        # time range (executor.go executeRowShard from/to handling)
        if f.options.type != FIELD_TYPE_TIME:
            raise ExecError(f"field {field_name} is not a time field")
        quantum = f.options.time_quantum
        from_t = timeq.parse_time(from_arg) if from_arg is not None else None
        to_t = timeq.parse_time(to_arg) if to_arg is not None else None
        if from_t is None or to_t is None:
            lo, hi = self._field_time_bounds(f)
            if lo is None:
                return None
            from_t = from_t or lo
            to_t = to_t or hi
        out = None
        for vname in timeq.views_by_time_range(VIEW_STANDARD, from_t, to_t, quantum):
            v = f.view(vname)
            if v is None:
                continue
            frag = v.fragment_if_exists(shard)
            if frag is None:
                continue
            w = frag.row_device(row_id)
            out = w if out is None else ob.b_or(out, w)
        return out

    def _field_time_bounds(self, f: Field):
        """Min/max time covered by the field's existing time views."""
        return timeq.min_max_view_times(f.views.keys(), f.options.time_quantum)

    def _field_arg_name(self, c: Call) -> str:
        for k in c.args:
            if not k.startswith("_") and k not in ("from", "to"):
                return k
        raise ExecError(f"{c.name}() argument required: field")

    def _row_bsi_shard(self, idx: Index, c: Call, shard: int):
        """BSI condition row (executor.go:1533 executeRowBSIGroupShard)."""
        conds = c.condition_args()
        if len(c.args) != 1 or len(conds) != 1:
            raise ExecError("Row(): exactly one condition required")
        field_name, cond = next(iter(conds.items()))
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        o = f.options
        bsiv = f.view(f.bsi_view_name())
        if bsiv is None:
            return None
        frag = bsiv.fragment_if_exists(shard)
        if frag is None:
            return None

        if cond.op == NEQ and cond.value is None:  # != null
            return frag.not_null()
        if cond.op == BETWEEN:
            lo, hi = cond.int_pair()
            blo, bhi, out_of_range = f.base_value_between(lo, hi)
            if out_of_range:
                return None
            if lo <= o.min and hi >= o.max:
                return frag.not_null()
            return frag.range_between(o.bit_depth, blo, bhi)

        if not isinstance(cond.value, int) or isinstance(cond.value, bool):
            raise ExecError("Row(): conditions only support integer values")
        value = cond.value
        op = _COND_OP_NAME[cond.op]
        base_value, out_of_range = f.base_value(op, value)
        if out_of_range and cond.op != NEQ:
            return None
        # full-range saturation -> notNull
        if (
            (cond.op == LT and value > o.max)
            or (cond.op == LTE and value >= o.max)
            or (cond.op == GT and value < o.min)
            or (cond.op == GTE and value <= o.min)
        ):
            return frag.not_null()
        if out_of_range and cond.op == NEQ:
            return frag.not_null()
        return frag.range_op(op, o.bit_depth, base_value)

    # ------------------------------------------------------------------
    # Count / Sum / Min / Max
    # ------------------------------------------------------------------

    def _execute_count(self, idx: Index, c: Call, shards) -> int:
        if len(c.children) != 1:
            raise ExecError("Count() only accepts a single bitmap input")
        shard_list = self._shards_for(idx, shards)
        total = 0
        memo: dict = {}
        for shard in shard_list:
            words = self._bitmap_call_shard(idx, c.children[0], shard, memo)
            if words is not None:
                total += int(ob.popcount(words))
        return total

    def _sum_filter_words(self, idx: Index, c: Call, shard: int):
        if len(c.children) == 1:
            return self._bitmap_call_shard(idx, c.children[0], shard), True
        filt = c.args.get("filter")
        if isinstance(filt, Call):
            return self._bitmap_call_shard(idx, filt, shard), True
        return None, False

    def _execute_sum(self, idx: Index, c: Call, shards) -> ValCount:
        field_name = c.string_arg("field") or self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        bsiv = f.view(f.bsi_view_name())
        total = 0
        count = 0
        if bsiv is not None:
            for shard in self._shards_for(idx, shards):
                frag = bsiv.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw, has_filter = self._sum_filter_words(idx, c, shard)
                if has_filter and fw is None:
                    continue
                s, n = frag.sum(fw, f.options.bit_depth)
                total += s
                count += n
        return ValCount(value=total + count * f.options.base, count=count)

    def _execute_min_max(self, idx: Index, c: Call, shards, is_min: bool) -> ValCount:
        field_name = c.string_arg("field") or self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        bsiv = f.view(f.bsi_view_name())
        best: Optional[Tuple[int, int]] = None
        if bsiv is not None:
            for shard in self._shards_for(idx, shards):
                frag = bsiv.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw, has_filter = self._sum_filter_words(idx, c, shard)
                if has_filter and fw is None:
                    continue
                val, cnt = (
                    frag.min(fw, f.options.bit_depth)
                    if is_min
                    else frag.max(fw, f.options.bit_depth)
                )
                if cnt == 0:
                    continue
                if best is None or (val < best[0] if is_min else val > best[0]):
                    best = (val, cnt)
                elif val == best[0]:
                    best = (val, best[1] + cnt)
        if best is None:
            return ValCount(0, 0)
        return ValCount(value=best[0] + f.options.base, count=best[1])

    def _execute_min_max_row(self, idx: Index, c: Call, shards, is_min: bool):
        """MinRow/MaxRow (executor.go:514-581)."""
        field_name = c.string_arg("field") or c.string_arg("_field")
        if field_name is None:
            field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        v = f.view(VIEW_STANDARD)
        filter_call = c.children[0] if c.children else None
        best_row = None
        best_count = 0
        if v is not None:
            for shard in self._shards_for(idx, shards):
                frag = v.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw = (
                    self._bitmap_call_shard(idx, filter_call, shard)
                    if filter_call
                    else None
                )
                if filter_call and fw is None:
                    continue
                ids = frag.row_ids()
                if not ids:
                    continue
                if filter_call is None:
                    rid = min(ids) if is_min else max(ids)
                    if (
                        best_row is None
                        or (rid < best_row if is_min else rid > best_row)
                    ):
                        best_row, best_count = rid, 1
                    continue
                counts = frag.row_counts(ids, fw)
                for rid, cnt in zip(ids, counts):
                    if cnt == 0:
                        continue
                    if (
                        best_row is None
                        or (rid < best_row if is_min else rid > best_row)
                    ):
                        best_row, best_count = rid, int(cnt)
                    elif rid == best_row:
                        best_count += int(cnt)
        return {"id": 0 if best_row is None else best_row, "count": best_count}

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def _execute_set(self, idx: Index, c: Call) -> bool:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("Set() column argument required (or keys not enabled)")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            value = c.int_arg(field_name)
            if value is None:
                raise ExecError("Set() int field requires an integer value")
            changed = f.set_value(col, value)
        else:
            row_id = c.args.get(field_name)
            if f.options.type == FIELD_TYPE_BOOL:
                if not isinstance(row_id, bool):
                    raise ExecError("Set() bool field requires true/false")
                row_id = 1 if row_id else 0
            if not isinstance(row_id, int):
                raise ExecError("Set() row argument required")
            ts = c.args.get("_timestamp")
            changed = f.set_bit(
                row_id, col, timeq.parse_time(ts) if ts is not None else None
            )
        idx.track_columns(np.array([col], np.uint64))
        return changed

    def _execute_clear(self, idx: Index, c: Call) -> bool:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("Clear() column argument required")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            return f.clear_value(col)
        row_id = c.args.get(field_name)
        if f.options.type == FIELD_TYPE_BOOL and isinstance(row_id, bool):
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            raise ExecError("Clear() row argument required")
        return f.clear_bit(row_id, col)

    def _execute_clear_row(self, idx: Index, c: Call, shards) -> bool:
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type not in ("set", "time", "mutex", "bool"):
            raise ExecError(f"ClearRow() is not supported on {f.options.type} fields")
        row_id = c.args.get(field_name)
        if f.options.type == FIELD_TYPE_BOOL and isinstance(row_id, bool):
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            raise ExecError("ClearRow() row argument required")
        changed = False
        for v in list(f.views.values()):
            for shard in self._shards_for(idx, shards):
                frag = v.fragment_if_exists(shard)
                if frag is None:
                    continue
                pos = frag.row_positions(row_id)
                if len(pos):
                    frag.import_positions(
                        None,
                        np.uint64(row_id) * np.uint64(SHARD_WIDTH)
                        + pos.astype(np.uint64),
                    )
                    changed = True
        return changed

    def _execute_store(self, idx: Index, c: Call, shards) -> bool:
        """Store(Row(...), f=row): overwrite a row with the result bitmap
        (executor.go:1937 executeSetRow)."""
        if len(c.children) != 1:
            raise ExecError("Store() requires a single bitmap input")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != "set":
            # reference executeSetRowShard (executor.go:1989) only allows set
            # fields — overwriting rows on mutex/bool would break the
            # one-row-per-column invariant, and BSI views aren't row-shaped.
            raise ExecError("Store() is only supported on set fields")
        row_id = c.args.get(field_name)
        if not isinstance(row_id, int):
            raise ExecError("Store() row argument required")
        v = f._view_create(VIEW_STANDARD)
        changed = False
        for shard in self._shards_for(idx, shards):
            words = self._bitmap_call_shard(idx, c.children[0], shard)
            new_pos = (
                ob.unpack_positions(np.asarray(words))
                if words is not None
                else np.empty(0, np.uint64)
            )
            frag = v.fragment(shard)
            old_pos = frag.row_positions(row_id).astype(np.uint64)
            to_set = np.setdiff1d(new_pos, old_pos)
            to_clear = np.setdiff1d(old_pos, new_pos)
            if len(to_set) or len(to_clear):
                base = np.uint64(row_id) * np.uint64(SHARD_WIDTH)
                frag.import_positions(
                    base + to_set if len(to_set) else None,
                    base + to_clear if len(to_clear) else None,
                )
                changed = True
        return changed

    def _execute_set_row_attrs(self, idx: Index, c: Call) -> None:
        field_name = c.args.get("_field")
        f = self._field_of(idx, field_name)
        row_id = c.args.get("_row")
        if not isinstance(row_id, int):
            raise ExecError("SetRowAttrs() row argument required")
        attrs = {
            k: v for k, v in c.args.items() if k not in ("_field", "_row")
        }
        f.row_attr_store.set_attrs(row_id, attrs)

    def _execute_set_column_attrs(self, idx: Index, c: Call) -> None:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("SetColumnAttrs() column argument required")
        attrs = {k: v for k, v in c.args.items() if k != "_col"}
        idx.column_attr_store.set_attrs(col, attrs)

    # ------------------------------------------------------------------
    # TopN (two-pass protocol, executor.go:860-999)
    # ------------------------------------------------------------------

    def _execute_topn(self, idx: Index, c: Call, shards, opt: ExecOptions) -> List[Pair]:
        ids_arg = c.args.get("ids")
        n = c.uint_arg("n")
        pairs = self._topn_shards(idx, c, shards)
        # ids/remote paths return untrimmed (reference executor.go:881): the
        # caller (or coordinating node) needs exact counts for every
        # candidate id to merge correctly.
        if not pairs or ids_arg or opt.remote:
            return pairs
        # Second pass: exact counts for the candidate ids.
        other = Call(c.name, dict(c.args), list(c.children))
        other.args["ids"] = sorted(p.id for p in pairs)
        trimmed = self._topn_shards(idx, other, shards)
        if n and len(trimmed) > n:
            trimmed = trimmed[:n]
        return trimmed

    def _topn_shards(self, idx: Index, c: Call, shards) -> List[Pair]:
        merged: Dict[int, int] = {}
        for shard in self._shards_for(idx, shards):
            for pair in self._topn_shard(idx, c, shard):
                merged[pair.id] = merged.get(pair.id, 0) + pair.count
        pairs = [Pair(id=i, count=cnt) for i, cnt in merged.items()]
        pairs.sort(key=lambda p: (-p.count, p.id))
        return pairs

    def _topn_shard(self, idx: Index, c: Call, shard: int) -> List[Pair]:
        field_name = c.args.get("_field")
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            raise ExecError(f"cannot compute TopN() on integer field: {field_name!r}")
        if f.options.cache_type == "none":
            raise ExecError(f'cannot compute TopN(), field has no cache: "{field_name}"')
        n = c.uint_arg("n")
        ids = c.args.get("ids")
        threshold = c.uint_arg("threshold") or DEFAULT_MIN_THRESHOLD
        src = None
        if len(c.children) == 1:
            src = self._bitmap_call_shard(idx, c.children[0], shard)
            if src is None:
                return []
        elif len(c.children) > 1:
            raise ExecError("TopN() can only have one input bitmap")
        v = f.view(VIEW_STANDARD)
        if v is None:
            return []
        frag = v.fragment_if_exists(shard)
        if frag is None:
            return []
        if ids:
            row_ids = [int(i) for i in ids]
        else:
            # Candidate pool = the fragment's rank cache (the reference's
            # approximation contract: rows evicted from the cache are not
            # TopN candidates; fragment.go:1570 top reads f.cache.Top()).
            # Cache counts are exact here (updated on every mutation), so
            # the unfiltered path needs no device pass at all.
            cached = frag.cache_top()
            if src is None:
                out = [
                    Pair(id=rid, count=cnt)
                    for rid, cnt in cached
                    if cnt >= threshold
                ]
                if n and len(out) > n * 2:
                    out = out[: n * 2]
                return out
            row_ids = [rid for rid, _ in cached]
        if not row_ids:
            return []
        counts = frag.row_counts(row_ids, src)
        out = [
            Pair(id=rid, count=int(cnt))
            for rid, cnt in zip(row_ids, counts)
            if cnt >= threshold
        ]
        out.sort(key=lambda p: (-p.count, p.id))
        # per-shard candidate pool: keep enough for a correct global top-n
        if n and not ids and len(out) > n * 2:
            out = out[: n * 2]
        return out

    # ------------------------------------------------------------------
    # Rows / GroupBy (executor.go:1068-1273)
    # ------------------------------------------------------------------

    def _execute_rows(self, idx: Index, c: Call, shards) -> List[int]:
        field_name = c.string_arg("field") or c.args.get("_field")
        if not field_name:
            raise ExecError("Rows() field required")
        col = c.uint_arg("column")
        if col is not None:
            shards = [col // SHARD_WIDTH]
        limit = c.uint_arg("limit")
        merged: set = set()
        for shard in self._shards_for(idx, shards):
            merged.update(self._rows_shard(idx, field_name, c, shard))
        out = sorted(merged)
        prev = c.uint_arg("previous")
        if prev is not None:
            out = [r for r in out if r > prev]
        if limit is not None:
            out = out[:limit]
        return out

    def _rows_shard(self, idx: Index, field_name: str, c: Call, shard: int) -> List[int]:
        f = self._field_of(idx, field_name)
        views = [VIEW_STANDARD]
        from_arg = c.args.get("from")
        to_arg = c.args.get("to")
        if f.options.type == FIELD_TYPE_TIME and (
            from_arg is not None or to_arg is not None or f.options.no_standard_view
        ):
            if not f.options.time_quantum:
                return []
            lo, hi = self._field_time_bounds(f)
            if lo is None:
                return []
            from_t = timeq.parse_time(from_arg) if from_arg is not None else lo
            to_t = timeq.parse_time(to_arg) if to_arg is not None else hi
            views = timeq.views_by_time_range(VIEW_STANDARD, from_t, to_t, f.options.time_quantum)
        col = c.uint_arg("column")
        if col is not None and col // SHARD_WIDTH != shard:
            return []
        out: set = set()
        for vname in views:
            v = f.view(vname)
            if v is None:
                continue
            frag = v.fragment_if_exists(shard)
            if frag is None:
                continue
            ids = frag.row_ids()
            if col is not None:
                ids = [r for r in ids if frag.contains(r, col % SHARD_WIDTH)]
            else:
                ids = [r for r in ids if frag.row_count(r) > 0]
            out.update(ids)
        return sorted(out)

    def _execute_group_by(self, idx: Index, c: Call, shards) -> List[GroupCount]:
        if not c.children:
            raise ExecError("need at least one child call")
        for child in c.children:
            if child.name != "Rows":
                raise ExecError(
                    f"'{child.name}' is not a valid child query for GroupBy, must be 'Rows'"
                )
        limit = c.uint_arg("limit")
        filter_call = c.args.get("filter")
        if filter_call is not None and not isinstance(filter_call, Call):
            raise ExecError("GroupBy filter must be a query")

        # Pre-fetch child row id lists (cluster-wide semantics).
        child_fields = []
        child_rows: List[List[int]] = []
        for child in c.children:
            fname = child.string_arg("field") or child.args.get("_field")
            child_fields.append(fname)
            child_rows.append(self._execute_rows(idx, child, shards))
            if not child_rows[-1]:
                return []

        merged: Dict[Tuple[int, ...], int] = {}
        for shard in self._shards_for(idx, shards):
            fw = (
                self._bitmap_call_shard(idx, filter_call, shard)
                if filter_call is not None
                else None
            )
            if filter_call is not None and fw is None:
                continue
            self._group_by_shard(
                idx, child_fields, child_rows, fw, shard, merged
            )
        out = [
            GroupCount(
                group=[
                    FieldRow(field=fn, row_id=rid)
                    for fn, rid in zip(child_fields, key)
                ],
                count=cnt,
            )
            for key, cnt in merged.items()
            if cnt > 0
        ]
        out.sort(key=lambda g: g.compare_key())
        offset = c.uint_arg("offset")
        if offset:
            out = out[offset:]
        if limit is not None:
            out = out[:limit]
        return out

    def _group_by_shard(
        self, idx, child_fields, child_rows, filter_words, shard, merged
    ) -> None:
        """Nested cross-product with zero-count pruning (the reference's
        groupByIterator, executor.go:3063)."""
        frags = []
        for fname in child_fields:
            f = self._field_of(idx, fname)
            v = f.view(VIEW_STANDARD)
            frag = v.fragment_if_exists(shard) if v is not None else None
            if frag is None:
                return
            frags.append(frag)

        def recurse(depth: int, acc_words, prefix: Tuple[int, ...]):
            frag = frags[depth]
            ids = [r for r in child_rows[depth] if frag.has_row(r)]
            if not ids:
                return
            counts = frag.row_counts(ids, acc_words)
            for rid, cnt in zip(ids, counts):
                if cnt == 0:
                    continue
                key = prefix + (rid,)
                if depth == len(frags) - 1:
                    merged[key] = merged.get(key, 0) + int(cnt)
                else:
                    words = frag.row_device(rid)
                    nxt = words if acc_words is None else ob.b_and(acc_words, words)
                    recurse(depth + 1, nxt, key)

        recurse(0, filter_words, ())

    # ------------------------------------------------------------------
    # Options (executor.go:360)
    # ------------------------------------------------------------------

    def _execute_options(self, idx: Index, c: Call, shards, opt: ExecOptions):
        if len(c.children) != 1:
            raise ExecError("Options() requires a single child query")
        new_opt = ExecOptions(
            remote=opt.remote,
            exclude_row_attrs=bool(c.args.get("excludeRowAttrs", opt.exclude_row_attrs)),
            exclude_columns=bool(c.args.get("excludeColumns", opt.exclude_columns)),
            column_attrs=bool(c.args.get("columnAttrs", opt.column_attrs)),
            max_writes=opt.max_writes,
        )
        s = c.args.get("shards")
        if s is not None:
            if not isinstance(s, list):
                raise ExecError("Options() shards must be a list")
            shards = [int(x) for x in s]
        return self._execute_call(idx, c.children[0], shards, new_opt)
