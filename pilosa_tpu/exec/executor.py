"""Query executor: per-call dispatch + per-shard map + reduce.

Reference: /root/reference/executor.go — executeCall dispatch (:274-339),
per-shard mapReduce (:2460-2613), per-call implementations (:360-2418).

TPU-first structure: every bitmap call lowers, per shard, to dense device
words; cross-child algebra happens on device; cross-shard reduction happens
with exact host ints (counts) or segment maps (rows). The single-node
executor walks shards in a Python loop — the mesh path (parallel/) stacks
shards into one [n_shards, W] sharded array and jits the whole map+reduce
with collectives; both share the per-shard lowering here.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pilosa_tpu.core import resultcache as rcache
from pilosa_tpu.core import timeq
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_TIME,
    Field,
)
from pilosa_tpu.core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT, BSI_SIGN_BIT
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec import translation
from pilosa_tpu.exec.plan import (
    BudgetExceeded,
    MultiCountPlan,
    PLeaf,
    PNary,
    PNode,
    PRangeBetween,
    PRangeCmp,
    PRangeEQ,
    PShift,
    PZero,
    SparseView,
    StackedPlan,
    Unsupported,
)
from pilosa_tpu.ops import bitmap as ob
from pilosa_tpu.pql import Call, Query, parse
from pilosa_tpu.pql.ast import BETWEEN, EQ, GT, GTE, LT, LTE, NEQ, Condition
from pilosa_tpu.shardwidth import SHARD_WIDTH

DEFAULT_MIN_THRESHOLD = 1  # reference: defaultMinThreshold, executor.go


class ExecError(Exception):
    pass


class NotFoundError(ExecError):
    pass


@dataclass
class ExecOptions:
    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    column_attrs: bool = False
    shards: Optional[List[int]] = None
    max_writes: int = 5000  # reference: MaxWritesPerRequest


@dataclass
class ColumnAttrSet:
    """Column attributes attached to a query response when columnAttrs=true
    (reference: ColumnAttrSet; executor.go:208 readColumnAttrSets)."""

    id: int = 0
    key: Optional[str] = None
    attrs: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"attrs": self.attrs or {}}
        if self.key is not None:
            out["key"] = self.key
        else:
            out["id"] = self.id
        return out


@dataclass
class QueryResponse:
    """Execute() response: per-call results plus optional column attr sets
    (reference: QueryResponse, executor.go:113-205). `profile` carries the
    assembled cross-node trace tree when the query ran with the
    `profile=true` option (server/api.py attaches it)."""

    results: List[Any]
    column_attr_sets: Optional[List[ColumnAttrSet]] = None
    profile: Optional[dict] = None


@dataclass
class Pair:
    """TopN result entry (reference: Pair, cache.go:317)."""

    id: int
    count: int
    key: Optional[str] = None

    def to_json(self):
        d = {"id": self.id, "count": self.count}
        if self.key is not None:
            d["key"] = self.key
        return d


@dataclass
class ValCount:
    """Sum/Min/Max result (reference: ValCount, executor.go)."""

    value: int
    count: int

    def to_json(self):
        return {"value": self.value, "count": self.count}


@dataclass
class FieldRow:
    field: str
    row_id: int
    row_key: Optional[str] = None

    def to_json(self):
        if self.row_key:
            return {"field": self.field, "rowKey": self.row_key}
        return {"field": self.field, "rowID": self.row_id}


@dataclass
class GroupCount:
    group: List[FieldRow]
    count: int

    def to_json(self):
        return {"group": [g.to_json() for g in self.group], "count": self.count}

    def compare_key(self):
        return tuple(g.row_id for g in self.group)


@dataclass
class _TopNSpec:
    """Parsed + validated TopN arguments, shared by the batched and
    per-shard paths (reference: fragment.go:1560 topOptions)."""

    f: Field
    n: int
    ids: Optional[list]
    threshold: int
    attr_name: Optional[str]
    filters: Optional[set]
    tanimoto: int
    src_call: Optional[Call]


# TopN dispatch accounting: tests assert the batched path issues O(1)
# device tallies per pass, never one per shard.
TOPN_STATS = {"batched": 0, "fallback": 0, "tally_evals": 0, "one_pass": 0}


class _TallyBundle:
    """Prepared filtered-TopN tally inputs (dense/sparse candidate split +
    device gather entries). Lives in the process-wide DEVICE_CACHE —
    thread-safe, HBM-budgeted, owner-invalidated — keyed by (view stack
    token, candidates, shards, fragment versions); `nbytes` makes the
    budget see the pinned device arrays."""

    __slots__ = ("dense_rows", "sparse_rows", "dev")

    def __init__(self, dense_rows, sparse_rows, dev):
        self.dense_rows = dense_rows
        self.sparse_rows = sparse_rows
        self.dev = dev

    @property
    def nbytes(self) -> int:
        if self.dev is None:
            return 64
        return sum(int(a.nbytes) for a in self.dev[:4])

# Per-shard fallback accounting: host reads are fused in chunks, so a
# 100-shard fallback query does ~2 device->host syncs, not 100.
FALLBACK_STATS = {"count_reads": 0}
_FALLBACK_READ_CHUNK = 64


_COND_OP_NAME = {EQ: "eq", NEQ: "neq", LT: "lt", LTE: "lte", GT: "gt", GTE: "gte"}

# Stacked (compiled mesh) query path: on by default; PILOSA_TPU_STACKED=0
# forces the per-shard fallback everywhere (debugging aid).
_STACKED_ENABLED = os.environ.get("PILOSA_TPU_STACKED", "1") in ("1", "true")


class _StackedLowering:
    """Lower a PQL bitmap call tree to a compiled plan over stacked
    [S, W] operands (exec/plan.py).

    Mirrors the per-shard lowering's semantic checks exactly — semantic
    errors raise ExecError (propagated to the caller identically on either
    path); shapes with no stacked form raise plan.Unsupported, which makes
    the executor fall back to the per-shard loop. Absent rows/views lower
    to PZero (all-zero stacks behave identically to the serial path's None:
    zero bits in, zero bits out)."""

    def __init__(
        self,
        ex: "Executor",
        idx: Index,
        shards: List[int],
        collect: bool = False,
        no_sparse_guard: bool = False,
    ):
        from pilosa_tpu.hbm import residency as hbm_res

        self.ex = ex
        self.idx = idx
        self.shards = list(shards)
        self.operands: List[Any] = []
        self.scalars: List[int] = []
        # extent pins taken while staging this lowering's operand stacks
        # (hbm/residency.py): ownership transfers to the lowered plan,
        # which releases them after its compiled dispatch; every failure
        # path below must release instead (no pin may outlive its query)
        self.extents = hbm_res.ExtentTable()
        self._call_memo: Dict[int, PNode] = {}
        self._leaf_memo: Dict[Tuple, Any] = {}
        # collect mode: walk the tree recording touched views (semantic
        # checks still raise) without building any stacks — the pre-pass
        # for compacted (sparse) lowering. no_sparse_guard: the shard list
        # was already compacted to present shards; only the budget applies.
        self.collect = collect
        self.no_sparse_guard = no_sparse_guard
        self.views: Dict[int, Any] = {}  # id(view) -> view, insertion order

    # -- operand registration ---------------------------------------------

    def _stack_guard(self, view, mult: int = 1) -> None:
        """Refuse stacked lowering when densifying would blow memory: a view
        materialized in few of many shards raises SparseView (recovered by
        compacted re-lowering), a stack bigger than a quarter of the device
        budget raises BudgetExceeded (recovered by shard-axis chunking —
        callers that can chunk must let it propagate, _chunk_by_budget)."""
        from pilosa_tpu.core.devcache import DEVICE_CACHE
        from pilosa_tpu.shardwidth import WORDS_PER_ROW

        n = len(self.shards)
        if n >= 64 and not self.no_sparse_guard:
            present = sum(
                1 for s in self.shards if view.fragment_if_exists(s) is not None
            )
            if present and present * 8 < n:
                raise SparseView("sparse view: stacked form would densify")
        if n * WORDS_PER_ROW * 4 * max(mult, 1) > DEVICE_CACHE.budget_bytes // 4:
            raise BudgetExceeded("stack exceeds device budget")

    def _view_leaf(self, view, row_id: int) -> PNode:
        key = ("row", id(view), row_id)
        node = self._leaf_memo.get(key)
        if node is None:
            self.views.setdefault(id(view), view)
            if self.collect:
                # pretend data exists everywhere so the whole tree is
                # walked and every reachable view is recorded
                node = PLeaf(0)
            else:
                self._stack_guard(view)
                arr = view.row_stack(row_id, self.shards, extents=self.extents)
                if arr is None:
                    node = PZero()
                else:
                    self.operands.append(arr)
                    node = PLeaf(len(self.operands) - 1)
            self._leaf_memo[key] = node
        return node

    def _plane_slot(self, view, bit_depth: int) -> Optional[int]:
        key = ("planes", id(view), bit_depth)
        if key not in self._leaf_memo:
            self.views.setdefault(id(view), view)
            if self.collect:
                self._leaf_memo[key] = 0
                return 0
            self._stack_guard(view, mult=bit_depth)
            arr = view.plane_stack(
                range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + bit_depth),
                self.shards,
                extents=self.extents,
            )
            if arr is None:
                self._leaf_memo[key] = None
            else:
                self.operands.append(arr)
                self._leaf_memo[key] = len(self.operands) - 1
        return self._leaf_memo[key]

    def _scalar(self, v: int) -> int:
        self.scalars.append(int(v))
        return len(self.scalars) - 1

    # -- call lowering ------------------------------------------------------

    def lower(self, c: Call) -> PNode:
        node = self._call_memo.get(id(c))
        if node is None:
            node = self._lower(c)
            self._call_memo[id(c)] = node
        return node

    def _lower(self, c: Call) -> PNode:
        name = c.name
        if name in ("Row", "Range"):
            return self._lower_row(c)
        if name == "Intersect":
            if not c.children:
                raise ExecError("empty Intersect query is currently not supported")
            ch = tuple(self.lower(x) for x in c.children)
            if any(isinstance(x, PZero) for x in ch):
                return PZero()
            return ch[0] if len(ch) == 1 else PNary("and", ch)
        if name in ("Union", "Xor"):
            ch = tuple(
                x
                for x in (self.lower(x) for x in c.children)
                if not isinstance(x, PZero)
            )
            if not ch:
                return PZero()
            if len(ch) == 1:
                return ch[0]
            return PNary("or" if name == "Union" else "xor", ch)
        if name == "Difference":
            if not c.children:
                return PZero()
            ch = tuple(self.lower(x) for x in c.children)
            if isinstance(ch[0], PZero):
                return PZero()
            rest = tuple(x for x in ch[1:] if not isinstance(x, PZero))
            if not rest:
                return ch[0]
            return PNary("andnot", (ch[0],) + rest)
        if name == "Not":
            if not self.idx.track_existence:
                raise ExecError("Not() query requires existence tracking to be enabled")
            if len(c.children) != 1:
                raise ExecError("Not() requires a single bitmap input")
            exists = self._existence_leaf()
            if isinstance(exists, PZero):
                return PZero()
            child = self.lower(c.children[0])
            if isinstance(child, PZero):
                return exists
            return PNary("andnot", (exists, child))
        if name == "All":
            return self._existence_leaf()
        if name == "Shift":
            if len(c.children) != 1:
                raise ExecError("Shift() requires a single bitmap input")
            n = c.int_arg("n")
            n = 1 if n is None else n
            child = self.lower(c.children[0])
            if isinstance(child, PZero):
                return PZero()
            return PShift(child, n, self._prev_idx())
        raise Unsupported(name)

    def _existence_leaf(self) -> PNode:
        ef = self.idx.existence_field()
        if ef is None:
            raise ExecError("existence field not available")
        v = ef.view(VIEW_STANDARD)
        if v is None:
            return PZero()
        return self._view_leaf(v, 0)

    def _prev_idx(self) -> Tuple[int, ...]:
        """Stack index of shard_id-1 per stack position (-1 = absent),
        padded out to the mesh-padded stack length."""
        from pilosa_tpu.parallel.mesh import padded_shards

        pos = {s: i for i, s in enumerate(self.shards)}
        out = [pos.get(s - 1, -1) for s in self.shards]
        out += [-1] * (padded_shards(len(self.shards)) - len(self.shards))
        return tuple(out)

    def _lower_row(self, c: Call) -> PNode:
        ex, idx = self.ex, self.idx
        if c.has_conditions():
            return self._lower_row_bsi(c)
        field_name = ex._field_arg_name(c)
        f = ex._field_of(idx, field_name)
        row_id = c.args.get(field_name)
        if isinstance(row_id, bool):
            if f.options.type != FIELD_TYPE_BOOL:
                raise ExecError("Row() bool value requires a bool field")
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            if isinstance(row_id, str):
                raise ExecError(
                    f"string row key {row_id!r} requires field keys (translation)"
                )
            raise ExecError("Row() must specify a row")
        if f.options.type == FIELD_TYPE_BOOL and row_id not in (0, 1):
            raise ExecError("Row() bool field expects row 0 or 1")

        from_arg = c.args.get("from")
        to_arg = c.args.get("to")
        if from_arg is None and to_arg is None:
            v = f.view(VIEW_STANDARD)
            if v is None:
                return PZero()
            return self._view_leaf(v, row_id)

        if f.options.type != FIELD_TYPE_TIME:
            raise ExecError(f"field {field_name} is not a time field")
        quantum = f.options.time_quantum
        from_t = timeq.parse_time(from_arg) if from_arg is not None else None
        to_t = timeq.parse_time(to_arg) if to_arg is not None else None
        if from_t is None or to_t is None:
            lo, hi = ex._field_time_bounds(f)
            if lo is None:
                return PZero()
            from_t = from_t or lo
            to_t = to_t or hi
        leaves = []
        for vname in timeq.views_by_time_range(VIEW_STANDARD, from_t, to_t, quantum):
            v = f.view(vname)
            if v is None:
                continue
            leaf = self._view_leaf(v, row_id)
            if not isinstance(leaf, PZero):
                leaves.append(leaf)
        if not leaves:
            return PZero()
        return leaves[0] if len(leaves) == 1 else PNary("or", tuple(leaves))

    def _lower_row_bsi(self, c: Call) -> PNode:
        """Stacked BSI condition row: same sign/saturation decomposition as
        Fragment.range_op/range_between (fragment.py), emitted as plan
        nodes over [D, S, W] plane stacks."""
        ex, idx = self.ex, self.idx
        conds = c.condition_args()
        if len(c.args) != 1 or len(conds) != 1:
            raise ExecError("Row(): exactly one condition required")
        field_name, cond = next(iter(conds.items()))
        f = ex._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        o = f.options
        bsiv = f.view(f.bsi_view_name())
        if bsiv is None:
            return PZero()
        exists = self._view_leaf(bsiv, BSI_EXISTS_BIT)
        if isinstance(exists, PZero):
            return PZero()
        sign = self._view_leaf(bsiv, BSI_SIGN_BIT)
        planes = self._plane_slot(bsiv, o.bit_depth)
        if planes is None:
            return PZero()

        if cond.op == NEQ and cond.value is None:  # != null
            return exists
        if cond.op == BETWEEN:
            lo, hi = cond.int_pair()
            blo, bhi, out_of_range = f.base_value_between(lo, hi)
            if out_of_range:
                return PZero()
            if lo <= o.min and hi >= o.max:
                return exists
            return self._between(exists, sign, planes, blo, bhi)

        if not isinstance(cond.value, int) or isinstance(cond.value, bool):
            raise ExecError("Row(): conditions only support integer values")
        value = cond.value
        op = _COND_OP_NAME[cond.op]
        base_value, out_of_range = f.base_value(op, value)
        if out_of_range and cond.op != NEQ:
            return PZero()
        if (
            (cond.op == LT and value > o.max)
            or (cond.op == LTE and value >= o.max)
            or (cond.op == GT and value < o.min)
            or (cond.op == GTE and value <= o.min)
        ):
            return exists
        if out_of_range and cond.op == NEQ:
            return exists
        return self._range_op(exists, sign, planes, op, base_value)

    @staticmethod
    def _pos_neg(exists: PNode, sign: PNode) -> Tuple[PNode, PNode]:
        return PNary("andnot", (exists, sign)), PNary("and", (exists, sign))

    def _range_op(self, exists, sign, planes: int, op: str, predicate: int) -> PNode:
        upred = self._scalar(abs(predicate))
        positives, negatives = self._pos_neg(exists, sign)
        if op in ("eq", "neq"):
            base = negatives if predicate < 0 else positives
            eq = PRangeEQ(base, planes, upred)
            if op == "eq":
                return eq
            return PNary("andnot", (exists, eq))
        if op in ("lt", "lte"):
            allow_eq = op == "lte"
            if predicate > 0 or (predicate == 0 and allow_eq):
                pos = PRangeCmp("lt", positives, planes, upred, allow_eq)
                return PNary("or", (negatives, pos))
            if predicate == 0:  # strict < 0
                return negatives
            return PRangeCmp("gt", negatives, planes, upred, allow_eq)
        if op in ("gt", "gte"):
            allow_eq = op == "gte"
            if predicate > 0 or (predicate == 0 and allow_eq):
                return PRangeCmp("gt", positives, planes, upred, allow_eq)
            if predicate == 0:  # strict > 0
                return PRangeCmp("gt", positives, planes, upred, False)
            neg = PRangeCmp("lt", negatives, planes, upred, allow_eq)
            return PNary("or", (positives, neg))
        raise ExecError(f"invalid range op {op!r}")

    def _between(self, exists, sign, planes: int, pmin: int, pmax: int) -> PNode:
        positives, negatives = self._pos_neg(exists, sign)
        if pmin >= 0:
            return PRangeBetween(
                positives, planes, self._scalar(abs(pmin)), self._scalar(abs(pmax))
            )
        if pmax < 0:
            return PRangeBetween(
                negatives, planes, self._scalar(abs(pmax)), self._scalar(abs(pmin))
            )
        pos = PRangeCmp("lt", positives, planes, self._scalar(abs(pmax)), True)
        neg = PRangeCmp("lt", negatives, planes, self._scalar(abs(pmin)), True)
        return PNary("or", (pos, neg))


# ---------------------------------------------------------------------------
# Versioned result cache (core/resultcache.py): eligibility surface.
# A call is cacheable when its referenced (field, view) set is STATICALLY
# enumerable — anything data-dependent (time-quantum view discovery) or
# version-blind (row attrs) makes it ineligible and it executes normally.
# ---------------------------------------------------------------------------

_CACHE_KINDS = {"Count": "count", "TopN": "topn", "GroupBy": "groupby"}
_CACHE_BITMAP_OK = frozenset(
    {"Row", "Union", "Intersect", "Difference", "Xor", "Not", "All",
     "Shift", "Range"}
)
# args whose presence means time-view discovery (data-dependent views)
_CACHE_TIME_ARGS = ("from", "to", "_start", "_end")
# TopN attrName/attrValues/tanimotoThreshold read row attrs / source
# counts outside the version vector — ineligible
_CACHE_TOPN_ARGS = frozenset({"_field", "n", "ids", "threshold"})
_CACHE_GROUPBY_ARGS = frozenset({"filter", "limit", "offset", "previous"})
_CACHE_ROWS_ARGS = frozenset({"_field", "field", "limit", "previous", "column"})


class _CacheCtx:
    """One call's cache context: the key, the referenced views, and the
    pre-execution version vector (None = uncacheable this round — the
    spec was eligible but the vector could not be assembled, e.g. a
    first sighting of an RPC-vector key or an unreachable peer)."""

    __slots__ = (
        "key", "kind", "views", "shard_list", "vector", "repair_spec",
        "dep_rows", "text", "index_name", "opt_remote", "call", "clocks",
        "hit", "hit_result",
    )

    def __init__(self, key, kind, views, shard_list, text, index_name,
                 repair_spec, dep_rows, opt_remote, call):
        self.key = key
        self.kind = kind
        self.views = views  # canonical sorted ((field, view), ...)
        self.shard_list = shard_list
        self.text = text
        self.index_name = index_name
        self.repair_spec = repair_spec
        self.dep_rows = dep_rows
        self.opt_remote = opt_remote
        self.call = call  # for per-node Shift shard-extension (distributed)
        self.vector = None
        self.clocks = None  # per-view mutation clocks, read pre-vector
        self.hit = False
        self.hit_result = None


class Executor:
    """Single-node executor. Cluster fan-out wraps this via the same
    per-shard lowering (reference: executor.go:44)."""

    def __init__(self, holder: Holder):
        self.holder = holder

    # ------------------------------------------------------------------
    # entry
    # ------------------------------------------------------------------

    def execute(
        self,
        index_name: str,
        query: Union[str, Query],
        shards: Optional[Sequence[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> List[Any]:
        return self.execute_response(index_name, query, shards, opt).results

    def execute_response(
        self,
        index_name: str,
        query: Union[str, Query],
        shards: Optional[Sequence[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> QueryResponse:
        """Execute and return the full response incl. column attr sets when
        columnAttrs=true (reference: executor.go:113-205 Execute)."""
        # private copy: Options(columnAttrs=...) mutates opt mid-query (the
        # reference's shared-opt behavior) and must not leak to the caller
        opt = dataclasses.replace(opt) if opt is not None else ExecOptions()
        if isinstance(query, str):
            query = parse(query)
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if query.write_call_n() > opt.max_writes:
            raise ExecError("too many writes in a single request")
        if shards is None:
            shards = opt.shards
        # key -> id translation (executor.go:2615 translateCalls); remote
        # (fan-out) requests arrive pre-translated by the coordinator.
        if not opt.remote:
            translation.translate_query(idx, query)
        results = []
        calls = query.calls
        cache_hits = 0
        i = 0
        while i < len(calls):
            # Batch maximal runs of adjacent Count calls into one multi-root
            # plan dispatch: shared operands are read from HBM once and the
            # per-dispatch fixed cost amortizes (~2x per-query at 4
            # counts/dispatch on v5e — the reference executes calls one by
            # one, executor.go:231).
            j = i
            while (
                j < len(calls)
                and calls[j].name == "Count"
                and len(calls[j].children) == 1
            ):
                j += 1
            if j - i >= 2 and self._counts_batchable(opt):
                # per-call result-cache interplay: the run is reads-only,
                # so every member's version vector can resolve up front;
                # cached members serve from host memory and only the
                # misses dispatch (whole-run batch when nothing hit)
                ctxs = [
                    self._cache_lookup(idx, cc, shards, opt)
                    for cc in calls[i:j]
                ]
                if any(cx is not None and cx.hit for cx in ctxs):
                    # serve the hits, and keep the MISSES batched: they
                    # are still adjacent Counts, so they ride one
                    # multi-root dispatch — one stale sibling must not
                    # degrade the other nine to per-call dispatches
                    miss = [
                        (cc, cx)
                        for cc, cx in zip(calls[i:j], ctxs)
                        if not (cx is not None and cx.hit)
                    ]
                    miss_results = None
                    if len(miss) >= 2:
                        miss_results = self._execute_count_batch(
                            idx, [cc for cc, _ in miss], shards, opt
                        )
                        if miss_results is not None:
                            for (_, cx), r in zip(miss, miss_results):
                                self._cache_store(idx, cx, r)
                    it = iter(miss_results or ())
                    for cc, cx in zip(calls[i:j], ctxs):
                        if cx is not None and cx.hit:
                            results.append(cx.hit_result)
                            cache_hits += 1
                        elif miss_results is not None:
                            results.append(next(it))
                        else:
                            r = self._execute_call(idx, cc, shards, opt)
                            self._cache_store(idx, cx, r)
                            results.append(r)
                    i = j
                    continue
                batch = self._execute_count_batch(idx, calls[i:j], shards, opt)
                if batch is not None:
                    for cx, r in zip(ctxs, batch):
                        self._cache_store(idx, cx, r)
                    results.extend(batch)
                else:
                    # no stacked form for some child: run the whole batch
                    # per-call (re-attempting ever-shorter batches would be
                    # O(run^2) lowering walks)
                    for cc, cx in zip(calls[i:j], ctxs):
                        r = self._execute_call(idx, cc, shards, opt)
                        self._cache_store(idx, cx, r)
                        results.append(r)
                i = j
                continue
            cx = self._cache_lookup(idx, calls[i], shards, opt)
            if cx is not None and cx.hit:
                results.append(cx.hit_result)
                cache_hits += 1
            else:
                r = self._execute_call(idx, calls[i], shards, opt)
                self._cache_store(idx, cx, r)
                results.append(r)
            i += 1
        if cache_hits:
            # flight-recorder attribution (a sub-millisecond p50 in the
            # histograms must be attributable, not mysterious): tag the
            # enclosing api.query span; profiles and the slow-query log
            # then show cache-served queries explicitly
            from pilosa_tpu.utils import tracing

            sp = tracing.active_span()
            if sp is not None:
                sp.set_tag("cache.hit", True)
                sp.set_tag("cache.hits", cache_hits)
        resp = QueryResponse(results=results)
        # Column attrs for every column in any Row result (executor.go:164;
        # Options(columnAttrs=...) mutates opt before we get here). Columns
        # excluded by excludeColumns have no segments, hence no attrs —
        # same interplay as the reference.
        if opt.column_attrs:
            cols: set = set()
            for r in results:
                if isinstance(r, Row):
                    cols.update(int(x) for x in r.columns().tolist())
            sets = []
            for col in sorted(cols):
                attrs = idx.column_attr_store.attrs(col)
                if attrs:
                    cas = ColumnAttrSet(id=col, attrs=attrs)
                    if idx.keys:
                        cas.key = idx.translate_store.key_for_id(col)
                        cas.id = 0
                    sets.append(cas)
            resp.column_attr_sets = sets
        # id -> key translation of results (executor.go:2786)
        if not opt.remote:
            resp.results = translation.translate_results(idx, query, results)
        return resp

    def _shards_for(self, idx: Index, shards, call: Optional[Call] = None) -> List[int]:
        if shards is not None:
            s = list(shards)
        else:
            s = sorted(idx.available_shards()) or [0]
        if call is not None:
            # Shift carries bits into following shards; materialize them even
            # when the index has no data there yet.
            k = self._count_shifts(call)
            if k:
                ext = set(s)
                for sh in s:
                    ext.update(range(sh + 1, sh + 1 + k))
                s = sorted(ext)
        return s

    # ------------------------------------------------------------------
    # versioned result cache (core/resultcache.py)
    # ------------------------------------------------------------------

    def _cache_spec(self, idx: Index, c: Call, shards, opt: ExecOptions):
        """Build the cache context for one call, or None when the call
        is ineligible (unknown shape, data-dependent views, attr reads).
        The key is (index scope token, canonical post-translation text,
        resolved shard list, remote flag): remote legs return different
        shapes (untrimmed TopN candidates) than coordinator results, so
        they cache under distinct keys."""
        kind = _CACHE_KINDS.get(c.name)
        if kind is None or rcache.RESULT_CACHE.budget_bytes <= 0:
            return None
        scope = getattr(idx, "_cache_scope", None)
        if scope is None:
            return None
        views: List[Tuple[str, str]] = []
        repair_spec = None
        try:
            if kind == "count":
                if len(c.children) != 1 or c.args:
                    return None
                if not self._cache_views(idx, c.children[0], views):
                    return None
                repair_spec = self._cache_repair_spec(c.children[0])
            elif kind == "topn":
                if not set(c.args) <= _CACHE_TOPN_ARGS or len(c.children) > 1:
                    return None
                fname = c.args.get("_field")
                if not isinstance(fname, str):
                    return None
                f = idx.field(fname)
                if f is None or f.options.type == FIELD_TYPE_TIME:
                    return None
                views.append((fname, VIEW_STANDARD))
                for child in c.children:
                    if not self._cache_views(idx, child, views):
                        return None
            else:  # groupby
                if not set(c.args) <= _CACHE_GROUPBY_ARGS:
                    return None
                if not c.children:
                    return None
                for child in c.children:
                    if child.name != "Rows":
                        return None
                    if not set(child.args) <= _CACHE_ROWS_ARGS:
                        return None
                    fname = child.args.get("field") or child.args.get("_field")
                    if not isinstance(fname, str):
                        return None
                    f = idx.field(fname)
                    if f is None or f.options.type == FIELD_TYPE_TIME:
                        return None
                    views.append((fname, VIEW_STANDARD))
                filt = c.args.get("filter")
                if isinstance(filt, Call) and not self._cache_views(
                    idx, filt, views
                ):
                    return None
            shard_list = tuple(self._shards_for(idx, shards, c))
        except Exception:  # noqa: BLE001 - eligibility is best-effort
            return None
        uniq = tuple(sorted(set(views)))
        if not uniq:
            return None
        dep_rows = self._cache_dep_rows(idx, c, kind)
        text = str(c)
        key = (scope, text, shard_list, bool(opt.remote))
        return _CacheCtx(
            key, kind, uniq, shard_list, text, idx.name, repair_spec,
            dep_rows, bool(opt.remote), c,
        )

    def _cache_views(self, idx: Index, c: Call, out: list) -> bool:
        """Collect the (field, view) pairs a bitmap tree reads; False
        when they are not statically enumerable (time-quantum ranges,
        TIME fields whose view set depends on data bounds, unknown call
        shapes)."""
        if any(k in c.args for k in _CACHE_TIME_ARGS):
            return False
        name = c.name
        if name in ("Union", "Intersect", "Difference", "Xor", "Shift"):
            pass
        elif name in ("Not", "All"):
            ef = idx.existence_field()
            if ef is None:
                return False
            out.append((ef.name, VIEW_STANDARD))
        elif name in ("Row", "Range"):
            conds = c.condition_args()
            if conds:
                if len(c.args) != 1 or len(conds) != 1 or c.children:
                    return False
                fname = next(iter(conds))
                f = idx.field(fname)
                if f is None or f.options.type == FIELD_TYPE_TIME:
                    return False
                out.append((fname, f.bsi_view_name()))
                return True
            args = [k for k in c.args if not k.startswith("_")]
            if len(args) != 1 or c.children:
                return False
            fname = args[0]
            rid = c.args[fname]
            if isinstance(rid, bool) or not isinstance(rid, int):
                return False  # untranslated key / call arg: let exec decide
            f = idx.field(fname)
            if f is None or f.options.type == FIELD_TYPE_TIME:
                return False
            out.append((fname, VIEW_STANDARD))
            return True
        else:
            return False
        for child in c.children:
            if not self._cache_views(idx, child, out):
                return False
        for v in c.args.values():
            if isinstance(v, Call) and not self._cache_views(idx, v, out):
                return False
        return True

    # monotone-tree repair leaf cap: op_popcount over the patch words is
    # O(leaves × changed words) host work per merged shard — past a few
    # operands a recompute through the normal dispatch path wins anyway
    _REPAIR_MAX_LEAVES = 8

    @staticmethod
    def _repair_leaf(c: Call) -> Optional[Tuple[str, str, int]]:
        """A plain translated Row(field=rid) — the only repairable leaf
        shape (BSI conditions and keyed rows read state the word delta
        does not carry)."""
        if c.name != "Row" or c.children or c.condition_args():
            return None
        args = [k for k in c.args if not k.startswith("_")]
        if len(args) != 1:
            return None
        rid = c.args[args[0]]
        if isinstance(rid, bool) or not isinstance(rid, int):
            return None
        return (args[0], VIEW_STANDARD, rid)

    @classmethod
    def _cache_repair_spec(cls, c: Call):
        """Count over a pure Intersect/Union tree of plain Rows (or one
        Row) is monotone-repairable: for set-only bursts the merge
        barrier's word deltas recompute `popcount(op(leaves))` over just
        the changed word indexes, and the telescoped per-shard delta
        patches the cached total in place (core/resultcache.py). Mixed
        nesting, Difference/Xor, BSI and Not fall back to
        revalidate-or-recompute. Returns ("and"|"or", (leaf, ...))."""
        lf = cls._repair_leaf(c)
        if lf is not None:
            return ("and", (lf,))
        if c.name not in ("Intersect", "Union") or c.args:
            return None
        if not 2 <= len(c.children) <= cls._REPAIR_MAX_LEAVES:
            return None
        leaves = []
        for ch in c.children:
            lf = cls._repair_leaf(ch)
            if lf is None:
                return None
            leaves.append(lf)
        return ("and" if c.name == "Intersect" else "or", tuple(leaves))

    def _cache_dep_rows(self, idx: Index, c: Call, kind: str):
        """Row-level dependency map for structural re-key:
        {(field, view): frozenset(row_ids) | None}, where None means the
        entry depends on ALL rows of that view (existence walks, BSI
        planes, TopN/GroupBy tally scans). A merge burst that provably
        touched no depended-on row of its view re-keys the entry to the
        merged versions without recompute (core/resultcache.py). Missing
        views behave as None on the cache side, so a partial map is
        safe — but the walk mirrors _cache_views, which already gated
        every shape that can reach here."""
        deps: Dict[Tuple[str, str], Optional[set]] = {}

        def dep_all(fname: str, vname: str) -> None:
            deps[(fname, vname)] = None

        def dep_row(fname: str, vname: str, rid: int) -> None:
            cur = deps.get((fname, vname), set())
            if cur is not None:
                cur.add(rid)
                deps[(fname, vname)] = cur

        def walk(call: Call) -> None:
            lf = self._repair_leaf(call)
            if lf is not None:
                dep_row(*lf)
                return
            if call.name in ("Row", "Range"):
                conds = call.condition_args()
                fname = next(iter(conds)) if conds else None
                f = idx.field(fname) if fname else None
                dep_all(fname, f.bsi_view_name() if f is not None else "")
                return
            if call.name in ("Not", "All"):
                ef = idx.existence_field()
                dep_all(ef.name if ef is not None else "", VIEW_STANDARD)
            for child in call.children:
                walk(child)
            for v in call.args.values():
                if isinstance(v, Call):
                    walk(v)

        try:
            if kind == "count":
                walk(c.children[0])
            elif kind == "topn":
                # the tally scan reads every row of the main field
                dep_all(c.args["_field"], VIEW_STANDARD)
                for child in c.children:
                    walk(child)
            else:  # groupby: each Rows() enumerates all rows of its field
                for child in c.children:
                    fname = child.args.get("field") or child.args.get("_field")
                    dep_all(fname, VIEW_STANDARD)
                filt = c.args.get("filter")
                if isinstance(filt, Call):
                    walk(filt)
        except Exception:  # noqa: BLE001 - dep map is an optimization only
            return None
        if not deps:
            return None
        return {
            k: (frozenset(v) if v is not None else None)
            for k, v in deps.items()
        }

    def local_version_vector(
        self, idx: Index, views, shard_list, node: str = ""
    ) -> tuple:
        """The exact fragment-version vector this node would read for
        `views` over `shard_list` — lock-free monotonic reads (every
        mutation funnel bumps Fragment.version, staged writes included).
        Elements carry the View's instance token so a delete/recreate
        can never alias an old entry back to life."""
        vec = []
        for fname, vname in views:
            f = idx.field(fname)
            if f is None:
                vec.append(("m", node, fname, ""))
                continue
            v = f.view(vname)
            if v is None:
                vec.append(("m", node, fname, vname))
                continue
            # hot loop (954 iterations per view on the bench geometry):
            # one local dict ref + .get per shard, no method dispatch
            frags = v.fragments
            versions = tuple(
                fr.version if (fr := frags.get(s)) is not None else -1
                for s in shard_list
            )
            vec.append(
                ("v", node, fname, vname, v._stack_token,
                 tuple(shard_list), versions)
            )
        return tuple(vec)

    def version_vector(
        self, idx: Index, ctx: _CacheCtx, opt: ExecOptions, expect=None
    ):
        """Single-node: the local vector IS the vector. The distributed
        executor overrides this with the fan-out's assembled vector
        (local + in-process mesh members + remote peers). `expect` is
        the store-path fast-fail hint: when the in-process parts
        already diverge from it, assembly may bail (None) without
        paying the remote version RPCs for a store that cannot
        happen — local collection is cheap, so the base class ignores
        it."""
        return self.local_version_vector(idx, ctx.views, ctx.shard_list)

    def clock_vector(self, idx: Index, ctx: _CacheCtx, opt: ExecOptions):
        """O(#views) revalidation fast path: one mutation-clock integer
        per referenced view (View.mutation_clock — bumped on every
        mutation event that bumps a fragment version). Clock-equal
        implies version-vector-equal, so the warm path never walks the
        shard axis. None disables the fast path (the distributed
        coordinator's entries span remote nodes whose clocks live
        behind an RPC that dominates anyway)."""
        vec = []
        for fname, vname in ctx.views:
            f = idx.field(fname)
            if f is None:
                vec.append(("m", "", fname, ""))
                continue
            v = f.view(vname)
            if v is None:
                vec.append(("m", "", fname, vname))
                continue
            vec.append(("c", v._stack_token, v.mutation_clock))
        return tuple(vec)

    def _cache_lookup(self, idx: Index, c: Call, shards, opt: ExecOptions):
        """Resolve one call against the result cache. Returns None when
        the call is ineligible; otherwise a _CacheCtx whose `hit` is set
        when the stored result revalidated (or was repaired in place by
        the read barrier this lookup ran)."""
        ctx = self._cache_spec(idx, c, shards, opt)
        if ctx is None:
            return None
        RC = rcache.RESULT_CACHE
        # clock fast path: clocks are read BEFORE any vector they might
        # arm, so a write racing the reads keeps the fast path disarmed
        # (live clock moved past) instead of ever serving stale
        clocks = ctx.clocks = self.clock_vector(idx, ctx, opt)
        found, res = RC.get_by_clock(ctx.key, clocks)
        if found:
            ctx.hit = True
            ctx.hit_result = res
            return ctx
        ctx.vector = self.version_vector(idx, ctx, opt)
        if ctx.vector is None:
            # unassemblable vector (first sighting of an RPC key, an
            # unreachable peer): a lookup happened and nothing served —
            # that is a miss on the dashboards, per observability.md
            RC.count_miss()
            return ctx
        # miss accounting is deferred to the END of the lookup: a
        # repaired serve is one hit, not a miss-then-hit (the repair
        # retry would otherwise pin cacheHitRate at 0.5 on a fully
        # cache-served dashboard)
        found, res = RC.get(ctx.key, ctx.vector, recount=False)
        if found:
            RC.refresh_clocks(ctx.key, clocks)
        elif (
            ctx.repair_spec is not None or ctx.dep_rows is not None
        ) and RC.repairable(ctx.key):
            # cheap repair: collect the current versions UNDER the read
            # barrier — sync_pending runs the merge barrier, which fires
            # note_merges and patches the cached Count from the burst's
            # word delta (count += popcount(delta & ~old)); if the entry
            # re-keyed to the live versions, serve it with zero
            # dispatches and zero operand re-reads
            clocks = ctx.clocks = self.clock_vector(idx, ctx, opt)
            self._cache_barrier(idx, ctx)
            vec2 = self.version_vector(idx, ctx, opt)
            if vec2 is not None:
                found, res = RC.get(ctx.key, vec2, recount=False)
                ctx.vector = vec2
                if found:
                    RC.refresh_clocks(ctx.key, clocks)
        if found:
            ctx.hit = True
            ctx.hit_result = res
        else:
            RC.count_miss()
        return ctx

    def _cache_barrier(self, idx: Index, ctx: _CacheCtx) -> None:
        """Run the read barrier over the call's referenced views (the
        same barrier execution would run first) so staged bursts merge
        and the repair hook fires."""
        for fname, vname in ctx.views:
            f = idx.field(fname)
            v = f.view(vname) if f is not None else None
            if v is not None:
                try:
                    v.sync_pending(shards=ctx.shard_list)
                except Exception:  # noqa: BLE001 - barrier is best-effort here
                    return

    def _cache_store(self, idx: Index, ctx, result) -> None:
        """Store a freshly computed result, guarded against racing
        writers: the vector is re-collected AFTER execution and the
        entry is stored only when it equals the pre-execution one —
        execution itself never bumps versions (barriers merge, stage
        bumps already happened), so inequality means a concurrent
        mutation landed mid-query and the result belongs to no single
        version state."""
        if ctx is None or ctx.vector is None or result is None:
            return
        opt = ExecOptions(remote=ctx.opt_remote)
        vec2 = self.version_vector(idx, ctx, opt, expect=ctx.vector)
        if vec2 != ctx.vector:
            return
        rcache.RESULT_CACHE.put(
            ctx.key, ctx.kind, ctx.index_name, ctx.text, result, ctx.vector,
            repair_spec=ctx.repair_spec, dep_rows=ctx.dep_rows,
            clocks=ctx.clocks,
        )

    # ------------------------------------------------------------------
    # prefetch warming (pilosa_tpu/hbm/)
    # ------------------------------------------------------------------

    _WARM_BITMAP = frozenset(
        {"Row", "Range", "Union", "Intersect", "Difference", "Xor", "Not",
         "All", "Shift"}
    )

    def warm(self, index_name: str, query, shards=None) -> int:
        """Stage a query's operand extents WITHOUT dispatching — the
        prefetch path (hbm/prefetch.py). Dispatches serialize behind
        plan._DISPATCH_MU but host->device staging does not, so a queued
        query's extents ride PCIe while the current dispatch runs.

        Best-effort by contract: every failure is swallowed (a warm miss
        costs only the staging the real query would do anyway), the query
        is deep-copied before translation (the admission-held original
        must not be mutated), and nothing is pinned past this call.
        Returns the number of call trees warmed (introspection/tests)."""
        import copy

        warmed = 0
        try:
            idx = self.holder.index(index_name)
            if idx is None:
                return 0
            q = (
                copy.deepcopy(query)
                if isinstance(query, Query)
                else parse(str(query))
            )
            translation.translate_query(idx, q)
            for c in q.calls:
                child = None
                if c.name == "Count" and len(c.children) == 1:
                    child = c.children[0]
                elif c.name in self._WARM_BITMAP:
                    child = c
                if child is None:
                    continue
                try:
                    shard_list = self._shards_for(idx, shards, child)
                    plans = self._lower_plans(idx, child, shard_list)
                except Exception:  # noqa: BLE001 - warming is best-effort
                    continue
                if plans:
                    for sp in plans:
                        sp.release_extents()
                    warmed += 1
        except Exception:  # noqa: BLE001 - warming must never raise
            pass
        return warmed

    # ------------------------------------------------------------------
    # dispatch (executor.go:274)
    # ------------------------------------------------------------------

    def _execute_call(self, idx: Index, c: Call, shards, opt: ExecOptions):
        name = c.name
        if name not in ("Set", "Clear", "SetRowAttrs", "SetColumnAttrs", "Options"):
            shards = self._shards_for(idx, shards, c)
        if name == "Sum":
            return self._execute_sum(idx, c, shards)
        if name == "Min":
            return self._execute_min_max(idx, c, shards, is_min=True)
        if name == "Max":
            return self._execute_min_max(idx, c, shards, is_min=False)
        if name == "MinRow":
            return self._execute_min_max_row(idx, c, shards, is_min=True)
        if name == "MaxRow":
            return self._execute_min_max_row(idx, c, shards, is_min=False)
        if name == "Clear":
            return self._execute_clear(idx, c)
        if name == "ClearRow":
            return self._execute_clear_row(idx, c, shards)
        if name == "Store":
            return self._execute_store(idx, c, shards)
        if name == "Count":
            return self._execute_count(idx, c, shards)
        if name == "Set":
            return self._execute_set(idx, c)
        if name == "SetRowAttrs":
            self._execute_set_row_attrs(idx, c)
            return None
        if name == "SetColumnAttrs":
            self._execute_set_column_attrs(idx, c)
            return None
        if name == "TopN":
            return self._execute_topn(idx, c, shards, opt)
        if name == "Rows":
            return self._execute_rows(idx, c, shards)
        if name == "GroupBy":
            return self._execute_group_by(idx, c, shards)
        if name == "Options":
            return self._execute_options(idx, c, shards, opt)
        return self._execute_bitmap_call(idx, c, shards, opt)

    # ------------------------------------------------------------------
    # bitmap calls
    # ------------------------------------------------------------------

    def _count_shifts(self, c: Call) -> int:
        n = 1 if c.name == "Shift" else 0
        n += sum(self._count_shifts(ch) for ch in c.children)
        n += sum(self._count_shifts(v) for v in c.args.values() if isinstance(v, Call))
        return n

    def _lower_stacked(self, idx: Index, c: Call, shard_list) -> Optional[StackedPlan]:
        """Try to lower a bitmap call tree to one compiled stacked plan
        (exec/plan.py; VERDICT round-1 task: the mesh IS the executor).
        Returns None when the call shape has no stacked form — the caller
        falls back to the per-shard loop. Semantic ExecErrors propagate.

        Sparse views (SparseView guard) re-lower over a COMPACTED shard
        list — only shards where some touched view is materialized, plus
        Shift relay successors — keeping the one-dispatch property while
        sparse shards stay free (reference: field.go:263-296)."""
        try:
            lowered = self._lower_roots(idx, [c], shard_list)
        except BudgetExceeded:
            return None  # callers that can chunk use _lower_plans instead
        if lowered is None:
            return None
        roots, low, n_out, out_shards = lowered
        return StackedPlan(
            roots[0], low.operands, low.scalars, n_out, out_shards,
            extents=low.extents,
        )

    def _lower_plans(self, idx: Index, c: Call, shard_list) -> Optional[List[StackedPlan]]:
        """One stacked plan when the operands fit the device budget; a
        handful of shard-axis-chunked plans when they don't (recursive
        halving) — NEVER the dispatch-per-shard loop just because the index
        is big. Returns None only for genuinely unsupported shapes."""
        if not _STACKED_ENABLED or not shard_list:
            return None

        def one(chunk):
            lowered = self._lower_roots(idx, [c], chunk, empty_ok=True)
            if lowered is None:
                return None
            if lowered == self._EMPTY_LOWER:
                return []
            roots, low, n_out, out_shards = lowered
            return [
                StackedPlan(
                    roots[0], low.operands, low.scalars, n_out, out_shards,
                    extents=low.extents,
                )
            ]

        return self._chunk_by_budget(list(shard_list), one)

    @staticmethod
    def _release_chunk_extents(items) -> None:
        """Unpin the extent tables of lowered-but-abandoned chunk results
        (plans carry one; BSI operand tuples already released theirs)."""
        for it in items or ():
            rel = getattr(it, "release_extents", None)
            if rel is not None:
                rel()

    @staticmethod
    def _chunk_by_budget(shard_list, lower_one):
        """Shared recursive halving for budget-exceeded lowering:
        lower_one(chunk) returns a list of per-chunk results ([] = empty
        range) or None for genuinely unsupported shapes; BudgetExceeded
        splits the shard axis until chunks fit (or bottoms out below 16
        shards, where the per-shard fallback takes over). A half that
        fails must not abandon the other half's lowered plans with their
        extent pins still held."""
        try:
            return lower_one(shard_list)
        except BudgetExceeded:
            if len(shard_list) < 16:
                return None  # can't subdivide usefully: per-shard fallback
            mid = len(shard_list) // 2
            left = Executor._chunk_by_budget(shard_list[:mid], lower_one)
            try:
                right = Executor._chunk_by_budget(shard_list[mid:], lower_one)
            except BaseException:
                Executor._release_chunk_extents(left)
                raise
            if left is None or right is None:
                Executor._release_chunk_extents(left)
                Executor._release_chunk_extents(right)
                return None
            return left + right

    _EMPTY_LOWER = "empty"  # sentinel: nothing materialized in this range

    def _lower_roots(self, idx: Index, calls: List[Call], shard_list, empty_ok: bool = False):
        """Lower one or more bitmap call trees over ONE shared operand set
        (shared leaf memo: an operand referenced by several calls is
        materialized once). Returns (roots, lowering, n_out, out_shards),
        None for per-shard fallback, or (with empty_ok) the _EMPTY_LOWER
        sentinel when no operand is materialized anywhere in the range;
        semantic ExecErrors propagate, BudgetExceeded propagates for
        shard-axis chunking."""
        if not _STACKED_ENABLED or not shard_list:
            return None
        shard_list = list(shard_list)
        # Shift reads the PREVIOUS shard's child bits for its carry
        # (serial path: _bitmap_call_shard(shard-1)); when the caller asked
        # for an explicit shard subset, those predecessors may hold data but
        # be absent from the list. Append them to the stack (depth-k shifts
        # need k predecessors); output trimming excludes them.
        k = max(self._count_shifts(c) for c in calls)
        if k:
            present = set(shard_list)
            extra = []
            for s in shard_list:
                for p in range(max(0, s - k), s):
                    if p not in present:
                        present.add(p)
                        extra.append(p)
            aug = shard_list + sorted(extra)
        else:
            aug = shard_list
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        low = _StackedLowering(self, idx, aug)
        try:
            # defer budget eviction across this query's operand staging:
            # making room for operand K by evicting operand K+1's extents
            # (LRU's cyclic-scan cascade) would re-upload the whole
            # working set every query (core/devcache.py deferred_eviction)
            with DEVICE_CACHE.deferred_eviction():
                roots = [low.lower(c) for c in calls]
        except SparseView:
            low.extents.release()
            return self._lower_roots_compacted(idx, calls, shard_list, aug, k)
        except BudgetExceeded:
            low.extents.release()
            raise  # recoverable by shard-axis chunking (_lower_plans)
        except Unsupported:
            low.extents.release()
            return None
        except BaseException:
            low.extents.release()  # semantic ExecErrors etc. propagate
            raise
        if not low.operands:
            # nothing materialized anywhere: trivial (empty) result
            low.extents.release()
            return self._EMPTY_LOWER if empty_ok else None
        return roots, low, len(shard_list), shard_list

    def _lower_roots_compacted(
        self, idx: Index, calls: List[Call], shard_list, aug, k: int
    ):
        """SparseView recovery: collect the views the trees touch (cheap
        no-stack walk), keep only shards where any of them is materialized
        (plus up-to-k Shift relay successors, which forward carries across
        gaps), and re-lower over that compacted list."""
        collect = _StackedLowering(self, idx, aug, collect=True)
        try:
            for c in calls:
                collect.lower(c)
        except Unsupported:
            return None
        views = list(collect.views.values())
        keep = {
            s
            for s in aug
            if any(v.fragment_if_exists(s) is not None for v in views)
        }
        if k:
            aug_set = set(aug)
            for s in sorted(keep):
                for t in range(s + 1, s + 1 + k):
                    if t in aug_set:
                        keep.add(t)
        compact = [s for s in aug if s in keep]
        if not compact:
            return None  # nothing anywhere: the serial loop is all-None
        req = set(shard_list)
        n_out = sum(1 for s in compact if s in req)
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        low = _StackedLowering(self, idx, compact, no_sparse_guard=True)
        try:
            with DEVICE_CACHE.deferred_eviction():
                roots = [low.lower(c) for c in calls]
        except BudgetExceeded:
            low.extents.release()
            raise  # recoverable by shard-axis chunking (_lower_plans)
        except Unsupported:
            low.extents.release()
            return None
        except BaseException:
            low.extents.release()
            raise
        if not low.operands:
            low.extents.release()
            return None
        # requested shards precede the aug extras in `compact`, so the
        # first n_out positions are exactly the kept requested shards
        return roots, low, n_out, compact[:n_out]

    def _execute_bitmap_call(
        self, idx: Index, c: Call, shards, opt: Optional[ExecOptions] = None
    ) -> Row:
        shard_list = self._shards_for(idx, shards)
        plans = self._lower_plans(idx, c, shard_list)
        if plans is not None:
            segments = {}
            try:
                for sp in plans:
                    stack = np.asarray(sp.rows())
                    for i, shard in enumerate(sp.out_shards):
                        if stack[i].any():
                            # copy: a slice view would pin the whole [S, W] stack
                            segments[shard] = stack[i].copy()
            finally:
                # a failing chunk must not leave later chunks' extents pinned
                for sp in plans:
                    sp.release_extents()
            return self._finish_bitmap_row(idx, c, Row(segments), opt)
        segments = {}
        memo: dict = {}
        for shard in shard_list:
            words = self._bitmap_call_shard(idx, c, shard, memo)
            if words is not None:
                segments[shard] = words
        return self._finish_bitmap_row(idx, c, Row(segments), opt)

    def _finish_bitmap_row(
        self, idx: Index, c: Call, row: Row, opt: Optional[ExecOptions]
    ) -> Row:
        """Attach row attrs to plain Row() results and honor
        excludeRowAttrs/excludeColumns (reference: executor.go:595-647
        executeBitmapCall tail; runs on the coordinator only — remote
        fan-out partials are merged and re-finished there)."""
        if opt is None or opt.remote:
            return row
        if c.name == "Row" and not any(
            isinstance(v, Condition) for v in c.args.values()
        ):
            if opt.exclude_row_attrs:
                row.attrs = {}
            else:
                fname = next(
                    (
                        k
                        for k in c.args
                        if not k.startswith("_") and k not in ("from", "to")
                    ),
                    None,
                )
                f = idx.field(fname) if fname else None
                if f is not None:
                    rid = c.args.get(fname)
                    if isinstance(rid, (int, np.integer)) and not isinstance(
                        rid, bool
                    ):
                        row.attrs = f.row_attr_store.attrs(int(rid))
        if opt.exclude_columns:
            row.segments = {}
        return row

    def _bitmap_call_shard(self, idx: Index, c: Call, shard: int, memo=None):
        """Lower one bitmap call for one shard to device words (or None).

        `memo` caches (call, shard) -> words within one query execution so a
        call subtree referenced twice (e.g. by Shift's cross-shard carry) is
        lowered once."""
        if memo is not None:
            key = (id(c), shard)
            if key in memo:
                return memo[key]
        words = self._bitmap_call_shard_uncached(idx, c, shard, memo)
        if memo is not None:
            memo[(id(c), shard)] = words
        return words

    # dispatch-ok escapes below: per-shard fallback path — single-device
    # row arrays (fragment.row_device), no mesh sharding, no collectives
    # to rendezvous
    def _bitmap_call_shard_uncached(  # dispatch-ok: per-shard path, single-device
        self, idx: Index, c: Call, shard: int, memo=None
    ):
        name = c.name
        if name in ("Row", "Range"):
            return self._row_shard(idx, c, shard)
        if name == "Intersect":
            return self._nary_shard(idx, c, shard, "intersect", memo)
        if name == "Union":
            return self._nary_shard(idx, c, shard, "union", memo)
        if name == "Difference":
            return self._nary_shard(idx, c, shard, "difference", memo)
        if name == "Xor":
            return self._nary_shard(idx, c, shard, "xor", memo)
        if name == "Not":
            return self._not_shard(idx, c, shard, memo)
        if name == "Shift":
            # Shift crosses shard boundaries: this shard's result is its own
            # child bits shifted up, OR'd with the overflow carried out of the
            # previous shard's child bits — composable per shard, so Shift
            # works nested inside any other call.
            if len(c.children) != 1:
                raise ExecError("Shift() requires a single bitmap input")
            n = c.int_arg("n")
            n = 1 if n is None else n
            cur = self._bitmap_call_shard(idx, c.children[0], shard, memo)
            out = None
            if cur is not None:
                out, _ = ob.shift_bits(cur, n)
            if shard > 0:
                prev = self._bitmap_call_shard(idx, c.children[0], shard - 1, memo)
                if prev is not None:
                    _, carry = ob.shift_bits(prev, n)
                    out = carry if out is None else ob.b_or(out, carry)
            return out
        if name == "All":
            return self._existence_words(idx, shard)
        raise ExecError(f"unknown call: {name}")

    def _nary_shard(  # dispatch-ok: per-shard path, single-device
        self, idx: Index, c: Call, shard: int, op: str, memo=None
    ):
        if not c.children:
            if op == "intersect":
                raise ExecError("empty Intersect query is currently not supported")
            return None
        words = [self._bitmap_call_shard(idx, ch, shard, memo) for ch in c.children]
        zero = None
        if op == "intersect":
            if any(w is None for w in words):
                return None
            out = words[0]
            for w in words[1:]:
                out = ob.b_and(out, w)
            return out
        if op == "union":
            present = [w for w in words if w is not None]
            if not present:
                return None
            out = present[0]
            for w in present[1:]:
                out = ob.b_or(out, w)
            return out
        if op == "difference":
            out = words[0]
            if out is None:
                return None
            for w in words[1:]:
                if w is not None:
                    out = ob.b_andnot(out, w)
            return out
        if op == "xor":
            present = [w for w in words if w is not None]
            if not present:
                return None
            out = present[0]
            for w in present[1:]:
                out = ob.b_xor(out, w)
            return out
        raise AssertionError(op)

    def _not_shard(  # dispatch-ok: per-shard path, single-device
        self, idx: Index, c: Call, shard: int, memo=None
    ):
        """Not via the existence field (executor.go:1734 executeNot)."""
        if not idx.track_existence:
            raise ExecError("Not() query requires existence tracking to be enabled")
        if len(c.children) != 1:
            raise ExecError("Not() requires a single bitmap input")
        exists = self._existence_words(idx, shard)
        if exists is None:
            return None
        child = self._bitmap_call_shard(idx, c.children[0], shard, memo)
        if child is None:
            return exists
        return ob.b_andnot(exists, child)

    def _existence_words(self, idx: Index, shard: int):
        ef = idx.existence_field()
        if ef is None:
            raise ExecError("existence field not available")
        v = ef.view(VIEW_STANDARD)
        if v is None:
            return None
        frag = v.fragment_if_exists(shard)
        return None if frag is None else frag.row_device(0)

    # -- Row / Range -------------------------------------------------------

    def _field_of(self, idx: Index, name: str) -> Field:
        f = idx.field(name)
        if f is None:
            raise NotFoundError(f"field not found: {name}")
        return f

    def _row_shard(  # dispatch-ok: per-shard path, single-device
        self, idx: Index, c: Call, shard: int
    ):
        if c.has_conditions():
            return self._row_bsi_shard(idx, c, shard)
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        row_id = c.args.get(field_name)
        if isinstance(row_id, bool):
            if f.options.type != FIELD_TYPE_BOOL:
                raise ExecError("Row() bool value requires a bool field")
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            if isinstance(row_id, str):
                raise ExecError(
                    f"string row key {row_id!r} requires field keys (translation)"
                )
            raise ExecError("Row() must specify a row")
        if f.options.type == FIELD_TYPE_BOOL and row_id not in (0, 1):
            raise ExecError("Row() bool field expects row 0 or 1")

        from_arg = c.args.get("from")
        to_arg = c.args.get("to")
        if from_arg is None and to_arg is None:
            v = f.view(VIEW_STANDARD)
            if v is None:
                return None
            frag = v.fragment_if_exists(shard)
            return None if frag is None else frag.row_device(row_id)

        # time range (executor.go executeRowShard from/to handling)
        if f.options.type != FIELD_TYPE_TIME:
            raise ExecError(f"field {field_name} is not a time field")
        quantum = f.options.time_quantum
        from_t = timeq.parse_time(from_arg) if from_arg is not None else None
        to_t = timeq.parse_time(to_arg) if to_arg is not None else None
        if from_t is None or to_t is None:
            lo, hi = self._field_time_bounds(f)
            if lo is None:
                return None
            from_t = from_t or lo
            to_t = to_t or hi
        out = None
        for vname in timeq.views_by_time_range(VIEW_STANDARD, from_t, to_t, quantum):
            v = f.view(vname)
            if v is None:
                continue
            frag = v.fragment_if_exists(shard)
            if frag is None:
                continue
            w = frag.row_device(row_id)
            out = w if out is None else ob.b_or(out, w)
        return out

    def _field_time_bounds(self, f: Field):
        """Min/max time covered by the field's existing time views."""
        return timeq.min_max_view_times(f.views.keys(), f.options.time_quantum)

    def _field_arg_name(self, c: Call) -> str:
        for k in c.args:
            if not k.startswith("_") and k not in ("from", "to"):
                return k
        raise ExecError(f"{c.name}() argument required: field")

    def _row_bsi_shard(self, idx: Index, c: Call, shard: int):
        """BSI condition row (executor.go:1533 executeRowBSIGroupShard)."""
        conds = c.condition_args()
        if len(c.args) != 1 or len(conds) != 1:
            raise ExecError("Row(): exactly one condition required")
        field_name, cond = next(iter(conds.items()))
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        o = f.options
        bsiv = f.view(f.bsi_view_name())
        if bsiv is None:
            return None
        frag = bsiv.fragment_if_exists(shard)
        if frag is None:
            return None

        if cond.op == NEQ and cond.value is None:  # != null
            return frag.not_null()
        if cond.op == BETWEEN:
            lo, hi = cond.int_pair()
            blo, bhi, out_of_range = f.base_value_between(lo, hi)
            if out_of_range:
                return None
            if lo <= o.min and hi >= o.max:
                return frag.not_null()
            return frag.range_between(o.bit_depth, blo, bhi)

        if not isinstance(cond.value, int) or isinstance(cond.value, bool):
            raise ExecError("Row(): conditions only support integer values")
        value = cond.value
        op = _COND_OP_NAME[cond.op]
        base_value, out_of_range = f.base_value(op, value)
        if out_of_range and cond.op != NEQ:
            return None
        # full-range saturation -> notNull
        if (
            (cond.op == LT and value > o.max)
            or (cond.op == LTE and value >= o.max)
            or (cond.op == GT and value < o.min)
            or (cond.op == GTE and value <= o.min)
        ):
            return frag.not_null()
        if out_of_range and cond.op == NEQ:
            return frag.not_null()
        return frag.range_op(op, o.bit_depth, base_value)

    # ------------------------------------------------------------------
    # Count / Sum / Min / Max
    # ------------------------------------------------------------------

    def _counts_batchable(self, opt: ExecOptions) -> bool:
        """Whether multi-Count batching may run locally (the distributed
        executor restricts it to remote/single-node execution, where the
        shard list is already this node's responsibility)."""
        return True

    def _execute_count_batch(
        self, idx: Index, calls: List[Call], shards, opt: Optional[ExecOptions] = None
    ) -> Optional[List[int]]:
        """N adjacent Count calls as ONE multi-root dispatch + one [N, S]
        host read. Returns None (caller falls back to per-call execution)
        when any child has no stacked form. `opt` lets the distributed
        override distinguish remote legs (local lowering) from
        coordinator-side batches (mesh-group lowering or per-call
        fan-out); the local path ignores it."""
        children = []
        for c in calls:
            if len(c.children) != 1:
                raise ExecError("Count() only accepts a single bitmap input")
            children.append(c.children[0])
        # every call must agree on its shard list (Shift calls extend
        # theirs with successor shards): evaluating one call over another's
        # extension would diverge from per-call execution on explicit
        # shard subsets
        lists = [self._shards_for(idx, shards, c) for c in calls]
        if any(lst != lists[0] for lst in lists[1:]):
            return None
        try:
            lowered = self._lower_roots(idx, children, lists[0])
        except BudgetExceeded:
            # per-call execution chunks each count by shard axis instead
            return None
        if lowered is None:
            return None
        roots, low, n_out, out_shards = lowered
        mp = MultiCountPlan(
            roots, low.operands, low.scalars, n_out, out_shards,
            extents=low.extents,
        )
        return mp.counts()

    def _execute_count(self, idx: Index, c: Call, shards) -> int:
        if len(c.children) != 1:
            raise ExecError("Count() only accepts a single bitmap input")
        shard_list = self._shards_for(idx, shards)
        child = c.children[0]
        if child.name in ("Row", "Range") and child.has_conditions():
            # single-BSI-condition counts ride the plane-streamed ladders
            # (exec/bsistream.py): slab-bounded plane residency, one
            # dispatch per slab, scalar halfword-pair reads — instead of
            # materializing the whole [D, S, W] stack through a plan
            from pilosa_tpu.exec import bsistream

            streamed = bsistream.count_range(self, idx, child, shard_list)
            if streamed is not None:
                return streamed
        plans = self._lower_plans(idx, child, shard_list)
        if plans is not None:
            # one jitted dispatch + one [S] host read per (budget-sized)
            # shard chunk — usually exactly one
            try:
                return sum(sp.count() for sp in plans)
            finally:
                for sp in plans:
                    sp.release_extents()
        # Per-shard fallback: the algebra still lowers shard-by-shard, but
        # counts are fetched in fused chunked reads (one [G] transfer per
        # _FALLBACK_READ_CHUNK shards) instead of one host sync per shard —
        # on tunneled hardware the syncs, not the dispatches, dominate
        # (VERDICT r2 #8; the pattern of the fused BSI aggregate read).
        total = 0
        memo: dict = {}
        pend: list = []
        for shard in shard_list:
            words = self._bitmap_call_shard(idx, c.children[0], shard, memo)
            if words is not None:
                pend.append(words)
                if len(pend) >= _FALLBACK_READ_CHUNK:
                    total += self._fused_count_read(pend)
                    pend = []
        if pend:
            total += self._fused_count_read(pend)
        return total

    @staticmethod
    def _fused_count_read(words_list) -> int:
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan as planmod

        FALLBACK_STATS["count_reads"] += 1
        planmod.STATS["host_reads"] += 1
        counts = ob.popcount_rows(jnp.stack(words_list))
        return int(np.asarray(counts, dtype=np.uint64).sum())

    def _sum_filter_words(self, idx: Index, c: Call, shard: int):
        if len(c.children) == 1:
            return self._bitmap_call_shard(idx, c.children[0], shard), True
        filt = c.args.get("filter")
        if isinstance(filt, Call):
            return self._bitmap_call_shard(idx, filt, shard), True
        return None, False

    _BSI_EMPTY = "empty"  # sentinel: no BSI data anywhere -> ValCount(0, 0)

    def _stacked_bsi(self, idx: Index, c: Call, f: Field, shard_list):
        """Stacked operands for a whole-field BSI aggregate (Sum/Min/Max):
        (exists, sign, planes, filter_or_None) as padded device stacks, the
        _BSI_EMPTY sentinel when there is trivially no data, or None to fall
        back to the per-shard loop."""
        if not _STACKED_ENABLED or not shard_list:
            return None
        bsiv = f.view(f.bsi_view_name())
        if bsiv is None:
            return self._BSI_EMPTY
        filter_call = None
        if len(c.children) == 1:
            filter_call = c.children[0]
        else:
            fa = c.args.get("filter")
            if isinstance(fa, Call):
                filter_call = fa
        if filter_call is not None and self._count_shifts(filter_call):
            # Shift carries need predecessor-shard augmentation (see
            # _lower_stacked); not worth plumbing here — fall back.
            return None
        # Shards without a BSI fragment contribute nothing to the aggregate
        # (the serial loop skips them), so compact the stack to present
        # shards — a sparse int field over many shards stays one dispatch.
        bsi_shards = [
            s for s in shard_list if bsiv.fragment_if_exists(s) is not None
        ]
        if not bsi_shards:
            return self._BSI_EMPTY
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        low = _StackedLowering(self, idx, bsi_shards, no_sparse_guard=True)
        try:
            with DEVICE_CACHE.deferred_eviction():
                low._stack_guard(bsiv, mult=f.options.bit_depth + 3)
                filt = None
                if filter_call is not None:
                    root = low.lower(filter_call)
                    if isinstance(root, PZero):
                        return self._BSI_EMPTY
                    if not low.operands:
                        return None
                    sp = StackedPlan(
                        root, low.operands, low.scalars, len(bsi_shards)
                    )
                    filt = sp.rows_full()
                exists = bsiv.row_stack(BSI_EXISTS_BIT, low.shards)
                if exists is None:
                    return self._BSI_EMPTY
                sign = bsiv.row_stack(BSI_SIGN_BIT, low.shards)
                planes = bsiv.plane_stack(
                    range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + f.options.bit_depth),
                    low.shards,
                )
        except BudgetExceeded:
            raise  # recoverable: _bsi_chunks halves the shard axis
        except Unsupported:
            return None
        finally:
            # extent pins here protect the staging window only (the
            # aggregate dispatches hold the assembled arrays themselves)
            low.extents.release()
        return exists, sign, planes, filt

    def _bsi_chunks(self, idx: Index, c: Call, f: Field, shard_list):
        """Stacked BSI operand sets, shard-axis-chunked under the device
        budget: a big int field costs a few dispatches, never one per
        shard. Returns a list of (exists, sign, planes, filt) tuples
        ([] = trivially empty), or None for per-shard fallback."""

        def one(chunk):
            st = self._stacked_bsi(idx, c, f, chunk)
            if st is None:
                return None
            if st == self._BSI_EMPTY:
                return []
            return [st]

        return self._chunk_by_budget(list(shard_list), one)

    def _execute_sum(self, idx: Index, c: Call, shards) -> ValCount:
        field_name = c.string_arg("field") or self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        from pilosa_tpu.exec import bsistream

        streamed = bsistream.aggregate(
            self, idx, c, f, self._shards_for(idx, shards), "sum"
        )
        if streamed is not None:
            return streamed
        chunks = self._bsi_chunks(idx, c, f, self._shards_for(idx, shards))
        if chunks is not None:
            # one jitted dispatch + one fused read per (budget-sized)
            # shard chunk — usually exactly one; exact host combine
            from pilosa_tpu.ops import bsi as obsi

            from pilosa_tpu.exec import plan as planmod

            depth = f.options.bit_depth
            count = 0
            total = 0
            for exists, sign, planes, filt in chunks:
                fused = np.asarray(
                    planmod.run_serialized(
                        lambda planes=planes, exists=exists, sign=sign,
                        filt=filt: obsi.sum_counts_stacked(
                            planes, exists, sign,
                            exists if filt is None else filt, depth
                        )
                    ),
                    dtype=np.uint64,
                )  # ONE device read: [1 + 2*depth, S]
                count += int(fused[0].sum())
                pos = fused[1 : 1 + depth].sum(axis=1)
                neg = fused[1 + depth :].sum(axis=1)
                total += sum(
                    (1 << i) * (int(pos[i]) - int(neg[i])) for i in range(depth)
                )
            return ValCount(value=total + count * f.options.base, count=count)
        bsiv = f.view(f.bsi_view_name())
        total = 0
        count = 0
        if bsiv is not None:
            for shard in self._shards_for(idx, shards):
                frag = bsiv.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw, has_filter = self._sum_filter_words(idx, c, shard)
                if has_filter and fw is None:
                    continue
                s, n = frag.sum(fw, f.options.bit_depth)
                total += s
                count += n
        return ValCount(value=total + count * f.options.base, count=count)

    def _execute_min_max(self, idx: Index, c: Call, shards, is_min: bool) -> ValCount:
        field_name = c.string_arg("field") or self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        from pilosa_tpu.exec import bsistream

        streamed = bsistream.aggregate(
            self, idx, c, f, self._shards_for(idx, shards),
            "min" if is_min else "max",
        )
        if streamed is not None:
            return streamed
        chunks = self._bsi_chunks(idx, c, f, self._shards_for(idx, shards))
        if chunks is not None:
            from pilosa_tpu.ops import bsi as obsi

            from pilosa_tpu.exec import plan as planmod

            best: Optional[Tuple[int, int]] = None  # (value, count)
            for exists, sign, planes, filt in chunks:
                fused = np.asarray(
                    planmod.run_serialized(
                        lambda planes=planes, exists=exists, sign=sign,
                        filt=filt: obsi.min_max_signed(
                            planes,
                            exists,
                            sign,
                            exists if filt is None else filt,
                            f.options.bit_depth,
                            is_min,
                        )
                    ),
                    dtype=np.uint64,
                )  # ONE device read: [magnitude, negative, any, counts...]
                if not fused[2]:
                    continue
                mag = int(fused[0])
                val = -mag if fused[1] else mag
                cnt = int(fused[3:].sum())
                if best is None or (val < best[0] if is_min else val > best[0]):
                    best = (val, cnt)
                elif val == best[0]:
                    best = (val, best[1] + cnt)
            if best is None:
                return ValCount(0, 0)
            return ValCount(value=best[0] + f.options.base, count=best[1])
        bsiv = f.view(f.bsi_view_name())
        best: Optional[Tuple[int, int]] = None
        if bsiv is not None:
            for shard in self._shards_for(idx, shards):
                frag = bsiv.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw, has_filter = self._sum_filter_words(idx, c, shard)
                if has_filter and fw is None:
                    continue
                val, cnt = (
                    frag.min(fw, f.options.bit_depth)
                    if is_min
                    else frag.max(fw, f.options.bit_depth)
                )
                if cnt == 0:
                    continue
                if best is None or (val < best[0] if is_min else val > best[0]):
                    best = (val, cnt)
                elif val == best[0]:
                    best = (val, best[1] + cnt)
        if best is None:
            return ValCount(0, 0)
        return ValCount(value=best[0] + f.options.base, count=best[1])

    def _execute_min_max_row(self, idx: Index, c: Call, shards, is_min: bool):
        """MinRow/MaxRow (executor.go:514-581). Filtered queries tally
        candidate rows against ONE stacked filter eval in extreme-end-first
        chunks with early stop — O(1..few) dispatches, not one per shard."""
        field_name = c.string_arg("field") or c.string_arg("_field")
        if field_name is None:
            field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        v = f.view(VIEW_STANDARD)
        filter_call = c.children[0] if c.children else None
        if filter_call is not None and v is not None:
            batched = self._min_max_row_batched(
                idx, v, filter_call, self._shards_for(idx, shards), is_min
            )
            if batched is not None:
                return batched
        best_row = None
        best_count = 0
        if v is not None:
            for shard in self._shards_for(idx, shards):
                frag = v.fragment_if_exists(shard)
                if frag is None:
                    continue
                fw = (
                    self._bitmap_call_shard(idx, filter_call, shard)
                    if filter_call
                    else None
                )
                if filter_call and fw is None:
                    continue
                ids = frag.row_ids()
                if not ids:
                    continue
                if filter_call is None:
                    rid = min(ids) if is_min else max(ids)
                    if (
                        best_row is None
                        or (rid < best_row if is_min else rid > best_row)
                    ):
                        best_row, best_count = rid, 1
                    continue
                counts = frag.row_counts(ids, fw)
                for rid, cnt in zip(ids, counts):
                    if cnt == 0:
                        continue
                    if (
                        best_row is None
                        or (rid < best_row if is_min else rid > best_row)
                    ):
                        best_row, best_count = rid, int(cnt)
                    elif rid == best_row:
                        best_count += int(cnt)
        return {"id": 0 if best_row is None else best_row, "count": best_count}

    def _min_max_row_batched(
        self, idx: Index, view, filter_call: Call, shard_list, is_min: bool
    ) -> Optional[dict]:
        """Filtered MinRow/MaxRow: candidates walk from the extreme end in
        tile-bounded chunks, each tallied against the stacked filter in one
        batched pass; the first row with any filtered bits wins."""
        present = [
            (s, frag)
            for s in shard_list
            if (frag := view.fragment_if_exists(s)) is not None
        ]
        if not present:
            return {"id": 0, "count": 0}
        lowered = self._stacked_filter(idx, filter_call, present)
        if lowered is None:
            return None
        present, sp = lowered
        if not present:
            return {"id": 0, "count": 0}
        src_stack = sp.rows_full()
        from pilosa_tpu.exec import plan as planmod

        if not bool(
            np.asarray(
                planmod.run_serialized(lambda: ob.popcount(src_stack))
            )
        ):
            # filter matched nothing anywhere: no candidate can score
            return {"id": 0, "count": 0}
        cand: set = set()
        for _, frag in present:
            cand.update(frag.row_ids())
        ordered = sorted(cand, reverse=not is_min)
        chunk = self._candidate_window(len(present))
        for i in range(0, len(ordered), chunk):
            ids = ordered[i : i + chunk]
            ic = self._topn_icounts(view, ids, present, src_stack)
            for rid in ids:
                total = int(ic[rid].sum())
                if total:
                    return {"id": rid, "count": total}
        return {"id": 0, "count": 0}

    @staticmethod
    def _candidate_window(n_shards: int) -> int:
        """Candidate rows per tally round for the extreme-end MinRow/
        MaxRow walk: derived from the same quarter-budget arithmetic as
        _chunk_by_budget (each candidate tallies against a [S, W] row
        stack) instead of a hardcoded 64 — wide clusters stop paying
        extra tally dispatches when the budget would fit more
        candidates, and narrow ones stop over-chunking tiny operands."""
        from pilosa_tpu.core.devcache import DEVICE_CACHE
        from pilosa_tpu.shardwidth import WORDS_PER_ROW

        row_bytes = max(1, n_shards) * WORDS_PER_ROW * 4
        cap = max(1, DEVICE_CACHE.budget_bytes // 4)
        return int(min(4096, max(16, cap // row_bytes)))

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def _execute_set(self, idx: Index, c: Call) -> bool:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("Set() column argument required (or keys not enabled)")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            value = c.int_arg(field_name)
            if value is None:
                raise ExecError("Set() int field requires an integer value")
            changed = f.set_value(col, value)
        else:
            row_id = c.args.get(field_name)
            if f.options.type == FIELD_TYPE_BOOL:
                if not isinstance(row_id, bool):
                    raise ExecError("Set() bool field requires true/false")
                row_id = 1 if row_id else 0
            if not isinstance(row_id, int):
                raise ExecError("Set() row argument required")
            ts = c.args.get("_timestamp")
            changed = f.set_bit(
                row_id, col, timeq.parse_time(ts) if ts is not None else None
            )
        idx.track_columns(np.array([col], np.uint64))
        return changed

    def _execute_clear(self, idx: Index, c: Call) -> bool:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("Clear() column argument required")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            return f.clear_value(col)
        row_id = c.args.get(field_name)
        if f.options.type == FIELD_TYPE_BOOL and isinstance(row_id, bool):
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            raise ExecError("Clear() row argument required")
        return f.clear_bit(row_id, col)

    def _execute_clear_row(self, idx: Index, c: Call, shards) -> bool:
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type not in ("set", "time", "mutex", "bool"):
            raise ExecError(f"ClearRow() is not supported on {f.options.type} fields")
        row_id = c.args.get(field_name)
        if f.options.type == FIELD_TYPE_BOOL and isinstance(row_id, bool):
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            raise ExecError("ClearRow() row argument required")
        changed = False
        for v in list(f.views.values()):
            for shard in self._shards_for(idx, shards):
                frag = v.fragment_if_exists(shard)
                if frag is None:
                    continue
                pos = frag.row_positions(row_id)
                if len(pos):
                    frag.import_positions(
                        None,
                        np.uint64(row_id) * np.uint64(SHARD_WIDTH)
                        + pos.astype(np.uint64),
                    )
                    changed = True
        return changed

    def _execute_store(self, idx: Index, c: Call, shards) -> bool:
        """Store(Row(...), f=row): overwrite a row with the result bitmap
        (executor.go:1937 executeSetRow)."""
        if len(c.children) != 1:
            raise ExecError("Store() requires a single bitmap input")
        field_name = self._field_arg_name(c)
        f = self._field_of(idx, field_name)
        if f.options.type != "set":
            # reference executeSetRowShard (executor.go:1989) only allows set
            # fields — overwriting rows on mutex/bool would break the
            # one-row-per-column invariant, and BSI views aren't row-shaped.
            raise ExecError("Store() is only supported on set fields")
        row_id = c.args.get(field_name)
        if not isinstance(row_id, int):
            raise ExecError("Store() row argument required")
        v = f._view_create(VIEW_STANDARD)
        changed = False
        for shard in self._shards_for(idx, shards):
            words = self._bitmap_call_shard(idx, c.children[0], shard)
            new_pos = (
                ob.unpack_positions(np.asarray(words))
                if words is not None
                else np.empty(0, np.uint64)
            )
            frag = v.fragment(shard)
            old_pos = frag.row_positions(row_id).astype(np.uint64)
            to_set = np.setdiff1d(new_pos, old_pos)
            to_clear = np.setdiff1d(old_pos, new_pos)
            if len(to_set) or len(to_clear):
                base = np.uint64(row_id) * np.uint64(SHARD_WIDTH)
                frag.import_positions(
                    base + to_set if len(to_set) else None,
                    base + to_clear if len(to_clear) else None,
                )
                changed = True
        return changed

    def _execute_set_row_attrs(self, idx: Index, c: Call) -> None:
        field_name = c.args.get("_field")
        f = self._field_of(idx, field_name)
        row_id = c.args.get("_row")
        if not isinstance(row_id, int):
            raise ExecError("SetRowAttrs() row argument required")
        attrs = {
            k: v for k, v in c.args.items() if k not in ("_field", "_row")
        }
        f.row_attr_store.set_attrs(row_id, attrs)

    def _execute_set_column_attrs(self, idx: Index, c: Call) -> None:
        col = c.args.get("_col")
        if not isinstance(col, int):
            raise ExecError("SetColumnAttrs() column argument required")
        attrs = {k: v for k, v in c.args.items() if k != "_col"}
        idx.column_attr_store.set_attrs(col, attrs)

    # ------------------------------------------------------------------
    # TopN (two-pass protocol, executor.go:860-999)
    # ------------------------------------------------------------------

    def _execute_topn(self, idx: Index, c: Call, shards, opt: ExecOptions) -> List[Pair]:
        ids_arg = c.args.get("ids")
        n = c.uint_arg("n")
        if not ids_arg and not opt.remote:
            # Local one-pass: the batched tally already computes exact
            # intersection counts for every candidate across every present
            # shard, so pass 2 is a pure host-side re-select over the same
            # [R, S] matrix — ONE device read per query instead of two.
            pairs = self._topn_local_full(idx, c, shards)
            if pairs is not None:
                if n and len(pairs) > n:
                    pairs = pairs[:n]
                return pairs
        pairs = self._topn_shards(idx, c, shards)
        # ids/remote paths return untrimmed (reference executor.go:881): the
        # caller (or coordinating node) needs exact counts for every
        # candidate id to merge correctly.
        if not pairs or ids_arg or opt.remote:
            return pairs
        # Second pass: exact counts for the candidate ids.
        other = Call(c.name, dict(c.args), list(c.children))
        other.args["ids"] = sorted(p.id for p in pairs)
        trimmed = self._topn_shards(idx, other, shards)
        if n and len(trimmed) > n:
            trimmed = trimmed[:n]
        return trimmed

    def _topn_local_full(self, idx: Index, c: Call, shards) -> Optional[List[Pair]]:
        """Both TopN passes (executor.go:860-999) against ONE device tally,
        with the host side fully vectorized.

        Pass 1 selects candidates per shard from the rank caches; the
        batched tally produces exact filter-intersection counts for the
        whole candidate union across all present shards, so the pass-2
        exact recount of the merged ids is answerable from the same
        [R, S] ic matrix alone (the classic cardinality prune is implied:
        ic <= cardinality always, so ic >= threshold decides every cell)
        — no second dispatch, no second read, and no per-(row, shard)
        Python loops (the classic per-shard heap walk only runs for
        shards whose survivor pool exceeds n, where the reference's
        early-stop semantics actually bind). Returns None when the filter
        child has no stacked form or the query uses Tanimoto (both fall
        back to the classic two-pass)."""
        spec = self._topn_parse(idx, c)
        if spec.src_call is None:
            return None  # hostfast path is already zero-dispatch
        if spec.tanimoto > 0:
            return None  # rare; per-shard src counts need their own read
        shard_list = self._shards_for(idx, shards)
        vp = self._topn_present(spec, shard_list)
        if vp is None:
            return []
        v, present = vp
        lowered = self._stacked_filter(idx, spec.src_call, present)
        if lowered is None:
            return None
        present, sp = lowered
        if not present:
            return []
        TOPN_STATS["one_pass"] += 1
        src_stack = sp.rows_full()  # one plan dispatch, stays on device
        thr = np.uint64(max(spec.threshold, 1))
        # Pass 1 survivors: vectorized threshold/attr prunes over the
        # memoized rank-cache arrays.
        tops = [frag.cache_top_arrays() for _, frag in present]
        allowed_of = None
        if spec.filters is not None:
            store = spec.f.row_attr_store
            uniq = np.unique(
                np.concatenate([r for r, _ in tops])
                if tops
                else np.empty(0, np.uint64)
            )
            ok = np.fromiter(
                (
                    (val := (store.attrs(int(rid)) or {}).get(spec.attr_name))
                    is not None
                    and val in spec.filters
                    for rid in uniq
                ),
                bool,
                len(uniq),
            )

            def allowed_of(rids):
                return ok[np.searchsorted(uniq, rids)]

        surv = []
        for rids, cnts in tops:
            m = cnts >= thr
            if allowed_of is not None and m.any():
                m &= allowed_of(rids)
            surv.append((rids[m], cnts[m]))
        if not any(len(s[0]) for s in surv):
            return []
        cand = np.unique(np.concatenate([s[0] for s in surv]))
        order, fused, bundle = self._topn_icounts_raw(
            v, [int(x) for x in cand], present, src_stack
        )
        # reindex the fused tally into cand (sorted) order
        pos_of = np.empty(len(order), np.int64)
        pos_of[np.searchsorted(cand, np.asarray(order, np.uint64))] = np.arange(
            len(order)
        )
        ic_mat = fused[pos_of]  # uint64[R, S] in cand order
        # Pass 1 select per shard. Fast path: when the survivor pool fits
        # in n, the heap never fills and selection degenerates to
        # "every survivor with ic >= max(threshold, 1)" — pure numpy.
        n1 = spec.n
        merged_mask = np.zeros(len(cand), bool)
        for j, (srids, scnts) in enumerate(surv):
            if not len(srids):
                continue
            pos = np.searchsorted(cand, srids)
            ic = ic_mat[pos, j]
            if n1 == 0 or len(srids) <= n1:
                merged_mask[pos[ic >= thr]] = True
                continue
            # exact cache-order walk preserving the reference's early-stop
            # semantics (fragment.go:1570-1704) for oversized pools
            taken = 0
            low = None
            for i in range(len(srids)):
                count = int(ic[i])
                if taken < n1:
                    if count < int(thr):
                        continue
                    merged_mask[pos[i]] = True
                    taken += 1
                    low = count if low is None or count < low else low
                    continue
                if low < int(thr) or int(scnts[i]) < low:
                    break
                if count < low:
                    continue
                merged_mask[pos[i]] = True
        if not merged_mask.any():
            return []
        # Pass 2: exact totals for the merged ids — pure matrix ops. The
        # explicit-ids semantics reduce to: a (row, shard) cell contributes
        # its intersection count iff it passes the threshold (the
        # cardinality prune is implied — ic <= cardinality always).
        sel = np.flatnonzero(merged_mask)
        take = ic_mat[sel] >= thr
        totals = (ic_mat[sel] * take).sum(axis=1, dtype=np.uint64)
        pairs = [
            Pair(id=int(cand[i]), count=int(t))
            for i, t in zip(sel, totals)
            if t > 0
        ]
        pairs.sort(key=lambda p: (-p.count, p.id))
        return pairs

    def _topn_parse(self, idx: Index, c: Call) -> "_TopNSpec":
        """Validate TopN args once per pass (semantic errors raise
        identically on the batched and per-shard paths)."""
        field_name = c.args.get("_field")
        f = self._field_of(idx, field_name)
        if f.options.type == FIELD_TYPE_INT:
            raise ExecError(f"cannot compute TopN() on integer field: {field_name!r}")
        if f.options.cache_type == "none":
            raise ExecError(f'cannot compute TopN(), field has no cache: "{field_name}"')
        tanimoto = c.uint_arg("tanimotoThreshold") or 0
        if tanimoto > 100:
            raise ExecError("Tanimoto Threshold is from 1 to 100 only")
        if len(c.children) > 1:
            raise ExecError("TopN() can only have one input bitmap")
        attr_name = c.args.get("attrName")
        attr_values = c.args.get("attrValues")
        filters = None
        if attr_name and attr_values:
            filters = {fv for fv in attr_values if fv is not None}
        return _TopNSpec(
            f=f,
            n=c.uint_arg("n") or 0,
            ids=c.args.get("ids"),
            threshold=c.uint_arg("threshold") or DEFAULT_MIN_THRESHOLD,
            attr_name=attr_name,
            filters=filters,
            tanimoto=tanimoto,
            src_call=c.children[0] if c.children else None,
        )

    def _topn_pool(self, spec: "_TopNSpec", frag) -> Tuple[int, list]:
        """One shard's candidate pool in rank order (fragment.go:1703
        topBitmapPairs): explicit ids read exact counts and disable
        truncation (n=0); otherwise the rank cache is the pool, already
        sorted by count. Counts are exact O(1) host metadata either way."""
        if spec.ids:
            ids = [int(i) for i in spec.ids]
            counts = frag.cache_counts_exact(np.asarray(ids, np.uint64))
            if counts is None:
                counts = frag.row_counts_host(ids)
            pairs = [(rid, int(cnt)) for rid, cnt in zip(ids, counts) if cnt > 0]
            pairs.sort(key=lambda p: (-p[1], p[0]))
            return 0, pairs
        return spec.n, frag.cache_top()

    def _topn_survivors(self, spec: "_TopNSpec", pairs, use_tan: bool, src_count: int):
        """Host-side prunes: the cache-count window/threshold and the attr
        filter read no device data (fragment.go:1610-1668)."""
        if use_tan:
            # exclusive count window around the Tanimoto-feasible region
            min_tan = src_count * spec.tanimoto / 100.0
            max_tan = src_count * 100.0 / spec.tanimoto
        survivors: List[Tuple[int, int]] = []
        for rid, cnt in pairs:
            if cnt == 0:
                continue
            if use_tan:
                if not (min_tan < cnt < max_tan):
                    continue
            elif cnt < spec.threshold:
                continue
            if spec.filters is not None:
                attr = spec.f.row_attr_store.attrs(rid)
                if not attr:
                    continue
                val = attr.get(spec.attr_name)
                if val is None or val not in spec.filters:
                    continue
            survivors.append((rid, cnt))
        return survivors

    @staticmethod
    def _topn_select(
        spec: "_TopNSpec",
        n: int,
        survivors,
        has_src: bool,
        src_count: int,
        icounts,
    ) -> List[Tuple[int, int]]:
        """The per-shard heap selection, mirroring fragment.top exactly
        (fragment.go:1570-1704): a min-heap caps the result at n with
        threshold-based early stop; cache rank order bounds remaining
        candidates once the result set is full. The decisions depend only
        on the (pre-computed) counts, so batching the count computation
        gives identical results. Returns (count, rid) tuples."""
        import heapq
        import math

        use_tan = spec.tanimoto > 0 and has_src
        results: List[Tuple[int, int]] = []  # min-heap of (count, rid)
        for rid, cnt in survivors:
            if n == 0 or len(results) < n:
                count = icounts[rid] if has_src else cnt
                if count == 0:
                    continue
                if use_tan:
                    t = math.ceil(count * 100 / (cnt + src_count - count))
                    if t <= spec.tanimoto:
                        continue
                elif count < spec.threshold:
                    continue
                heapq.heappush(results, (count, rid))
                if n > 0 and len(results) == n and not has_src:
                    break
                continue
            # Result set full: only counts above the current minimum can
            # displace; cache rank order bounds remaining candidates.
            low = results[0][0]
            if low < spec.threshold or cnt < low:
                break
            count = icounts[rid]
            if count < low:
                continue
            heapq.heappush(results, (count, rid))
        return results

    def _topn_shards(self, idx: Index, c: Call, shards) -> List[Pair]:
        spec = self._topn_parse(idx, c)
        shard_list = self._shards_for(idx, shards)
        merged = self._topn_merged_batched(idx, spec, shard_list)
        if merged is None:
            merged = {}
            TOPN_STATS["fallback"] += 1
            for shard in shard_list:
                for count, rid in self._topn_shard(idx, spec, shard):
                    merged[rid] = merged.get(rid, 0) + count
        pairs = [Pair(id=i, count=cnt) for i, cnt in merged.items()]
        pairs.sort(key=lambda p: (-p.count, p.id))
        return pairs

    def _topn_merged_batched(
        self, idx: Index, spec: "_TopNSpec", shard_list
    ) -> Optional[Dict[int, int]]:
        """All shards' TopN tallies in one batched pass (VERDICT r2 #1: the
        last host-bound query family goes device-first).

        Candidate *selection* stays on the rank caches (exact O(1) host
        metadata — unlike the reference's approximate caches, recounting
        plain candidates is free here, fragment.go:1570 top). Only a filter
        bitmap needs device work: the child lowers to ONE stacked plan
        eval, and the survivors' intersection counts are tallied as
        popcount(planes & src) in O(candidates/tile) chunked dispatches
        with a single host read — never one dispatch per shard. Returns
        None when the child has no stacked form (per-shard fallback)."""
        vp = self._topn_present(spec, shard_list)
        if vp is None:
            return {}
        v, present = vp
        has_src = spec.src_call is not None
        if not has_src:
            TOPN_STATS["batched"] += 1
            return self._topn_merged_hostfast(spec, present)
        lowered = self._stacked_filter(idx, spec.src_call, present)
        if lowered is None:
            return None
        present, sp = lowered
        TOPN_STATS["batched"] += 1
        if not present:
            return {}
        src_stack = sp.rows_full()  # one plan dispatch, stays on device
        src_counts = None
        if spec.tanimoto > 0:
            from pilosa_tpu.exec import plan as planmod

            TOPN_STATS["tally_evals"] += 1
            src_counts = np.asarray(
                planmod.run_serialized(lambda: ob.popcount_rows(src_stack)),
                dtype=np.uint64,
            )[: len(present)]
        # Per-shard pools + host-side survivor prunes.
        pools = []
        cand_union: Dict[int, None] = {}  # insertion-ordered set
        use_tan = spec.tanimoto > 0
        for j, (shard, frag) in enumerate(present):
            n, pairs = self._topn_pool(spec, frag)
            sc = int(src_counts[j]) if use_tan else 0
            survivors = self._topn_survivors(spec, pairs, use_tan, sc)
            pools.append((n, survivors, sc))
            for rid, _ in survivors:
                cand_union[rid] = None
        ic_rows: Dict[int, np.ndarray] = {}
        if cand_union:
            # canonical (sorted) candidate order: pass 2's ids are sorted,
            # so both passes chunk identically and the pass-1 plane-stack
            # cache entries are REUSED — unsorted chunks doubled the
            # host->device transfer footprint and thrashed the HBM budget
            # at bench scale (3.6 s/query vs ~0.3 s warm)
            ic_rows = self._topn_icounts(v, sorted(cand_union), present, src_stack)
        merged: Dict[int, int] = {}
        for j, (n, survivors, sc) in enumerate(pools):
            icounts = {rid: int(ic_rows[rid][j]) for rid, _ in survivors}
            for count, rid in self._topn_select(
                spec, n, survivors, True, sc, icounts
            ):
                merged[rid] = merged.get(rid, 0) + count
        return merged

    def _topn_merged_hostfast(self, spec: "_TopNSpec", present) -> Dict[int, int]:
        """The no-filter-bitmap merge: counts are exact O(1) host metadata,
        so both passes reduce to vectorized metadata walks — zero device
        dispatches. Semantics identical to _topn_pool/_topn_survivors/
        _topn_select with has_src=False (the differential tests force the
        general path and compare)."""
        merged: Dict[int, int] = {}
        allowed = None
        if spec.filters is not None:
            store = spec.f.row_attr_store
            memo: Dict[int, bool] = {}

            def allowed(rid: int) -> bool:
                ok = memo.get(rid)
                if ok is None:
                    attr = store.attrs(rid)
                    val = attr.get(spec.attr_name) if attr else None
                    ok = memo[rid] = val is not None and val in spec.filters
                return ok

        if spec.ids:
            # pass 2 / explicit ids: no truncation -> the per-shard select
            # reduces to "sum counts >= threshold per shard" (exact).
            # Cardinalities come from the rank cache when it is provably
            # complete (vectorized lookup), else the authoritative
            # row_counts_host walk.
            ids = [int(i) for i in spec.ids]
            if allowed is not None:
                ids = [rid for rid in ids if allowed(rid)]
            if not ids:
                return merged
            ids_arr = np.asarray(ids, np.uint64)
            totals = np.zeros(len(ids), np.uint64)
            thr = np.uint64(spec.threshold)
            for _, frag in present:
                c = frag.cache_counts_exact(ids_arr)
                if c is None:
                    c = frag.row_counts_host(ids)
                c[c < thr] = 0
                totals += c
            for rid, cnt in zip(ids, totals):
                if cnt:
                    merged[rid] = merged.get(rid, 0) + int(cnt)
            return merged
        # pass 1: per-shard top-n of the rank cache, fully vectorized: the
        # cache arrays are sorted descending so the threshold cut is a
        # prefix, the attr filter is a boolean mask, and the n-bound is a
        # cumsum cut — same contract as the select heap with no src. The
        # merge is one bincount over the concatenated selections.
        n = spec.n
        thr = np.uint64(max(spec.threshold, 1))
        sel_rids, sel_cnts = [], []
        for _, frag in present:
            rids, cnts = frag.cache_top_arrays()
            end = int(np.searchsorted(-cnts.view(np.int64), -int(thr), "right"))
            rids, cnts = rids[:end], cnts[:end]
            if allowed is not None and len(rids):
                m = np.fromiter((allowed(int(r)) for r in rids), bool, len(rids))
                rids, cnts = rids[m], cnts[m]
            if n and len(rids) > n:
                rids, cnts = rids[:n], cnts[:n]
            if len(rids):
                sel_rids.append(rids)
                sel_cnts.append(cnts)
        if sel_rids:
            all_r = np.concatenate(sel_rids)
            all_c = np.concatenate(sel_cnts).astype(np.uint64)
            uniq, inv = np.unique(all_r, return_inverse=True)
            totals = np.bincount(inv, weights=all_c.astype(np.float64))
            # float64 weights are exact below 2^53; per-row totals are
            # bounded by n_shards * SHARD_WIDTH, far under that
            for rid, t in zip(uniq, totals):
                merged[int(rid)] = int(t)
        return merged

    def _topn_present(self, spec: "_TopNSpec", shard_list):
        """Shared TopN preamble: (standard view, present fragments), or
        None when the view or every listed fragment is absent."""
        v = spec.f.view(VIEW_STANDARD)
        if v is None:
            return None
        present = [
            (s, frag)
            for s in shard_list
            if (frag := v.fragment_if_exists(s)) is not None
        ]
        if not present:
            return None
        # cross-fragment merge barrier: rank caches and tally bundles are
        # about to read every present fragment — merge the whole staged
        # burst as one batched pass, not one host pass per fragment
        v.sync_pending(frags=[frag for _, frag in present])
        return (v, present)

    def _stacked_filter(self, idx: Index, filter_call: Call, present):
        """Lower a filter bitmap over the present (shard, fragment) pairs
        for a batched tally. Returns (present, plan) with `present`
        restricted to the plan's out_shards when compaction dropped shards
        — those have no filter bits anywhere, so they contribute nothing
        (the per-shard paths skip None filter words the same way). None =
        no stacked form (per-shard fallback)."""
        pshards = [s for s, _ in present]
        sp = self._lower_stacked(idx, filter_call, pshards)
        if sp is None:
            return None
        if sp.out_shards != pshards:
            outs = set(sp.out_shards)
            present = [(s, frag) for s, frag in present if s in outs]
        return present, sp

    def _topn_icounts(
        self, view, cand: List[int], present, src_stack
    ) -> Dict[int, np.ndarray]:
        order, fused, _ = self._topn_icounts_raw(view, cand, present, src_stack)
        return {rid: fused[k] for k, rid in enumerate(order)}

    def _topn_icounts_raw(
        self, view, cand: List[int], present, src_stack
    ) -> Tuple[List[int], np.ndarray, "_TallyBundle"]:
        """Intersection counts for every candidate row across all present
        shards with ONE blocking device read (per-chunk reads would cost
        one tunnel RTT each): (row order, uint64[R, S] matrix). Candidates
        split by host representation: rows sparse in every present shard
        contribute only their live words (device gather + sorted-segment
        cumsum — HBM traffic ~ bytes of live words, not full zero-padded
        planes, and no TPU scatter); rows dense anywhere go through
        chunked [R_c, S, W] plane stacks. All partial counts concatenate
        on device into a single fused [R, S] read."""
        from pilosa_tpu.exec import groupby as gb

        import jax.numpy as jnp

        pshards = tuple(s for s, _ in present)
        n_present = len(present)
        s_pad, w = src_stack.shape
        bundle = self._topn_tally_bundle(view, cand, present, w)
        dense_rows, sparse_rows, dev = (
            bundle.dense_rows,
            bundle.sparse_rows,
            bundle.dev,
        )
        from pilosa_tpu.exec import plan as planmod

        parts = []  # device uint32 [*, n_present] blocks (materialized)
        order: List[int] = []  # row ids aligned with the fused row axis
        if dense_rows:
            r_c = gb._gmax(s_pad, w)
            for i in range(0, len(dense_rows), r_c):
                ids = dense_rows[i : i + r_c]
                pad_ids = [int(x) for x in gb._pad_pow2(np.asarray(ids))]
                # staging OUTSIDE the dispatch mutex (transfers overlap
                # the in-flight program; they don't rendezvous)
                planes = view.plane_stack(pad_ids, pshards)
                src = src_stack
                if planes.shape[1] != s_pad:
                    # stacked src may carry extra Shift-predecessor shards
                    src = src_stack[: planes.shape[1]]
                TOPN_STATS["tally_evals"] += 1
                # tally programs consume mesh-sharded stacks: serialized
                # like every other compiled dispatch (plan.run_serialized)
                parts.append(
                    planmod.run_serialized(
                        lambda src=src, planes=planes, n=len(ids):
                        gb._counts_cross(src[None], planes)[0][:n, :n_present]
                    )
                )
                order.extend(ids)
        if sparse_rows:
            if dev is None:
                parts.append(
                    jnp.zeros((len(sparse_rows), n_present), jnp.uint32)
                )
            else:
                idx, mask, starts, ends, r_pad, s_pow2 = dev
                TOPN_STATS["tally_evals"] += 1
                parts.append(
                    planmod.run_serialized(
                        lambda: ob.gather_tally_sorted(
                            src_stack, idx, mask, starts, ends
                        ).reshape(r_pad, s_pow2)[: len(sparse_rows), :n_present]
                    )
                )
            order.extend(sparse_rows)
        if not order:
            return [], np.empty((0, n_present), np.uint64), bundle
        fused = np.asarray(
            parts[0]
            if len(parts) == 1
            else planmod.run_serialized(
                lambda: jnp.concatenate(parts, axis=0)
            ),
            dtype=np.uint64,
        )
        return order, fused, bundle

    def _topn_tally_bundle(self, view, cand: List[int], present, w: int) -> "_TallyBundle":
        """Prepared inputs for the candidate tally (see _TallyBundle).

        Sparse rows' live bits are folded to per-(row, shard) word entries
        in ONE vectorized host pass (sort + reduceat over every bit of
        every sparse candidate — no per-(row, shard) numpy calls), then
        cached in DEVICE_CACHE keyed by fragment versions, so warm queries
        skip the host build entirely. No cardinality data is stored: the
        pass-2 cardinality prune is implied by ic <= cardinality, so the
        ic matrix alone decides every cell (Tanimoto, which genuinely
        needs per-shard cardinalities, takes the classic two-pass)."""
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        key = view._stack_key(
            "topn_sparse", tuple(cand), tuple(s for s, _ in present)
        )
        return DEVICE_CACHE.get_or_build(
            key,
            lambda: self._topn_tally_build(cand, present, w),
            index=view.index,
        )

    def _topn_tally_build(self, cand: List[int], present, w: int) -> "_TallyBundle":
        import jax

        r_all = len(cand)
        n_present = len(present)
        cats, lens = [], []
        for _, frag in present:
            c_, l_ = frag.rows_sparse_concat(cand)
            cats.append(c_)
            lens.append(l_)
        lens_mat = np.stack(lens)  # [S, R]; -1 marks dense-rep
        dense_mask = (lens_mat < 0).any(axis=0)
        n_bits = int(np.clip(lens_mat, 0, None).sum())
        if n_bits >= 1 << 27:
            # uint32 cumsum headroom (gather_tally_sorted): route everything
            # through the plane path instead
            dense_mask = np.ones(r_all, bool)
        dense_rows = [rid for i, rid in enumerate(cand) if dense_mask[i]]
        sparse_rows = [rid for i, rid in enumerate(cand) if not dense_mask[i]]
        dev = None
        if sparse_rows:
            # pow2-pad BOTH segment axes (rows and shards): every distinct
            # input shape forces a fresh XLA compile of gather_tally_sorted,
            # so shapes must come from a log-bounded family
            s_pow2 = 1 << max(n_present - 1, 0).bit_length()
            k_of = np.full(r_all, -1, np.int64)
            k_of[~dense_mask] = np.arange(len(sparse_rows))
            wkey_parts, bit_parts = [], []
            for j in range(n_present):
                l_ = np.clip(lens_mat[j], 0, None)
                if not l_.sum():
                    continue
                rows_per_el = np.repeat(np.arange(r_all), l_)
                keep = ~dense_mask[rows_per_el]
                pos = cats[j][keep].astype(np.int64)
                seg = k_of[rows_per_el[keep]] * s_pow2 + j
                wkey_parts.append(seg * w + (pos >> 5))
                bit_parts.append(
                    np.uint32(1) << (pos & np.int64(31)).astype(np.uint32)
                )
            if wkey_parts:
                wkeys = np.concatenate(wkey_parts)
                bits = np.concatenate(bit_parts)
                o = np.argsort(wkeys, kind="stable")
                sk, sb = wkeys[o], bits[o]
                new_grp = np.empty(len(sk), bool)
                new_grp[0] = True
                np.not_equal(sk[1:], sk[:-1], out=new_grp[1:])
                gstart = np.flatnonzero(new_grp)
                masks = np.bitwise_or.reduceat(sb, gstart)
                uk = sk[gstart]
                seg_of = uk // w
                idx = ((seg_of % s_pow2) * w + uk % w).astype(np.int32)
                # pad the entry axis to pow2 too; padding lands after every
                # segment end, so sums are unaffected
                k_pad = 1 << max(len(idx) - 1, 0).bit_length()
                if k_pad != len(idx):
                    padn = k_pad - len(idx)
                    idx = np.concatenate([idx, np.zeros(padn, np.int32)])
                    masks = np.concatenate([masks, np.zeros(padn, np.uint32)])
                r_pad = 1 << max(len(sparse_rows) - 1, 0).bit_length()
                segs = np.arange(r_pad * s_pow2)
                starts = np.searchsorted(seg_of, segs, "left").astype(np.int32)
                ends = np.searchsorted(seg_of, segs, "right").astype(np.int32)
                dev = (
                    jax.device_put(idx),
                    jax.device_put(masks),
                    jax.device_put(starts),
                    jax.device_put(ends),
                    r_pad,
                    s_pow2,
                )
        return _TallyBundle(dense_rows, sparse_rows, dev)

    def _topn_shard(self, idx: Index, spec: "_TopNSpec", shard: int) -> List[Tuple[int, int]]:
        """One shard's TopN candidates (the per-shard fallback when the
        filter child has no stacked form). Same pool/prune/select pipeline
        as the batched path; intersection counts for surviving candidates
        come from one batched per-shard dispatch."""
        src = None
        if spec.src_call is not None:
            src = self._bitmap_call_shard(idx, spec.src_call, shard)
            if src is None:
                return []
        v = spec.f.view(VIEW_STANDARD)
        if v is None:
            return []
        frag = v.fragment_if_exists(shard)
        if frag is None:
            return []
        n, pairs = self._topn_pool(spec, frag)
        if not pairs:
            return []
        has_src = src is not None
        src_count = int(ob.popcount(src)) if has_src else 0
        use_tan = spec.tanimoto > 0 and has_src
        survivors = self._topn_survivors(spec, pairs, use_tan, src_count)
        icounts: Optional[Dict[int, int]] = None
        if has_src and survivors:
            cand = [rid for rid, _ in survivors]
            icounts = {
                rid: int(cnt) for rid, cnt in zip(cand, frag.row_counts(cand, src))
            }
        return self._topn_select(spec, n, survivors, has_src, src_count, icounts)

    # ------------------------------------------------------------------
    # Rows / GroupBy (executor.go:1068-1273)
    # ------------------------------------------------------------------

    def _execute_rows(self, idx: Index, c: Call, shards) -> List[int]:
        field_name = c.string_arg("field") or c.args.get("_field")
        if not field_name:
            raise ExecError("Rows() field required")
        col = c.uint_arg("column")
        if col is not None:
            shards = [col // SHARD_WIDTH]
        limit = c.uint_arg("limit")
        merged: set = set()
        for shard in self._shards_for(idx, shards):
            merged.update(self._rows_shard(idx, field_name, c, shard))
        out = sorted(merged)
        prev = c.uint_arg("previous")
        if prev is not None:
            out = [r for r in out if r > prev]
        if limit is not None:
            out = out[:limit]
        return out

    def _rows_shard(self, idx: Index, field_name: str, c: Call, shard: int) -> List[int]:
        f = self._field_of(idx, field_name)
        views = [VIEW_STANDARD]
        from_arg = c.args.get("from")
        to_arg = c.args.get("to")
        if f.options.type == FIELD_TYPE_TIME and (
            from_arg is not None or to_arg is not None or f.options.no_standard_view
        ):
            if not f.options.time_quantum:
                return []
            lo, hi = self._field_time_bounds(f)
            if lo is None:
                return []
            from_t = timeq.parse_time(from_arg) if from_arg is not None else lo
            to_t = timeq.parse_time(to_arg) if to_arg is not None else hi
            views = timeq.views_by_time_range(VIEW_STANDARD, from_t, to_t, f.options.time_quantum)
        col = c.uint_arg("column")
        if col is not None and col // SHARD_WIDTH != shard:
            return []
        out: set = set()
        for vname in views:
            v = f.view(vname)
            if v is None:
                continue
            frag = v.fragment_if_exists(shard)
            if frag is None:
                continue
            ids = frag.row_ids()
            if col is not None:
                ids = [r for r in ids if frag.contains(r, col % SHARD_WIDTH)]
            else:
                ids = [r for r in ids if frag.row_count(r) > 0]
            out.update(ids)
        return sorted(out)

    def _execute_group_by(self, idx: Index, c: Call, shards) -> List[GroupCount]:
        if not c.children:
            raise ExecError("need at least one child call")
        for child in c.children:
            if child.name != "Rows":
                raise ExecError(
                    f"'{child.name}' is not a valid child query for GroupBy, must be 'Rows'"
                )
        limit = c.uint_arg("limit")
        filter_call = c.args.get("filter")
        if filter_call is not None and not isinstance(filter_call, Call):
            raise ExecError("GroupBy filter must be a query")

        # Pagination cursor: per-child Rows(previous=) args plus the
        # GroupBy-level previous=[...] list form; both resume the sorted
        # cross-product strictly after the previous group (reference
        # groupByIterator seek, executor.go:3121-3160 — per-child Seek with
        # wrap/ignorePrev cascades is equivalent to a lexicographic ">"
        # against the tuple (prev_i or first-row_i)).
        prevs: List[Optional[int]] = [ch.uint_arg("previous") for ch in c.children]
        gprev = c.args.get("previous")
        if gprev is not None:
            # shape errors surface in translate_call (translation.py) before
            # execution; this guard only covers direct programmatic calls
            if not isinstance(gprev, list) or len(gprev) != len(c.children):
                raise ExecError(
                    "GroupBy previous must be a list with one entry per child"
                )
            for i, pv in enumerate(gprev):
                if prevs[i] is None:
                    prevs[i] = int(pv)
        has_prev = any(p is not None for p in prevs)

        # Pre-fetch child row id lists (cluster-wide semantics). Without a
        # child limit/column, the previous arg must NOT prune the row list:
        # a non-last child's previous row still heads later groups (e.g.
        # (prev, prev+1, ...)) — the cursor is applied to whole group tuples
        # below. WITH limit or column the reference prefetches via
        # executeRows, which applies previous before limit (executor.go:
        # 1101-1115 + 1403), so the pruned list is the group row universe.
        child_fields = []
        child_rows: List[List[int]] = []
        for child in c.children:
            fname = child.string_arg("field") or child.args.get("_field")
            child_fields.append(fname)
            saved_prev = None
            if "limit" not in child.args and "column" not in child.args:
                saved_prev = child.args.pop("previous", None)
            try:
                child_rows.append(self._execute_rows(idx, child, shards))
            finally:
                if saved_prev is not None:
                    child.args["previous"] = saved_prev
            if not child_rows[-1]:
                return []

        anchor: Optional[Tuple[int, ...]] = None
        if has_prev:
            # The reference seek position: children without a previous value
            # anchor at their first row, the last child seeks one past its
            # previous value, and the landing group itself is included —
            # i.e. the result keeps group tuples >= the anchor tuple.
            last = len(c.children) - 1
            anchor = tuple(
                (prevs[i] + (1 if i == last else 0))
                if prevs[i] is not None
                else child_rows[i][0]
                for i in range(len(c.children))
            )
            # Any tuple with first component < anchor[0] compares below the
            # anchor regardless of deeper values, so the first child's rows
            # can be pruned before tallying — deep pages skip the bulk of
            # the cross-product instead of tallying and discarding it.
            child_rows[0] = [r for r in child_rows[0] if r >= anchor[0]]
            if not child_rows[0]:
                return []

        shard_list = self._shards_for(idx, shards)
        merged = self._group_by_stacked(
            idx, child_fields, child_rows, filter_call, shard_list
        )
        if merged is None:
            merged = {}
            for shard in shard_list:
                fw = (
                    self._bitmap_call_shard(idx, filter_call, shard)
                    if filter_call is not None
                    else None
                )
                if filter_call is not None and fw is None:
                    continue
                self._group_by_shard(
                    idx, child_fields, child_rows, fw, shard, merged
                )
        if anchor is not None:
            merged = {k: v for k, v in merged.items() if k >= anchor}
        out = [
            GroupCount(
                group=[
                    FieldRow(field=fn, row_id=rid)
                    for fn, rid in zip(child_fields, key)
                ],
                count=cnt,
            )
            for key, cnt in merged.items()
            if cnt > 0
        ]
        out.sort(key=lambda g: g.compare_key())
        offset = c.uint_arg("offset")
        if offset:
            out = out[offset:]
        if limit is not None:
            out = out[:limit]
        return out

    def _group_by_stacked(
        self, idx, child_fields, child_rows, filter_call, shard_list
    ) -> Optional[Dict[Tuple[int, ...], int]]:
        """Tally the whole GroupBy cross-product in O(depth) batched device
        dispatches over stacked [R, S, W] operands (exec/groupby.py),
        replacing the per-(prefix, depth) dispatch + host sync of the
        recursive walk. Returns None to fall back to the per-shard path
        (stacked lowering unsupported for this shape/budget)."""
        if not _STACKED_ENABLED or not shard_list:
            return None
        if filter_call is not None and self._count_shifts(filter_call):
            return None
        child_views = []
        for fname in child_fields:
            f = self._field_of(idx, fname)
            v = f.view(VIEW_STANDARD)
            if v is None:
                return {}
            child_views.append(v)
        # A shard contributes a group only when EVERY child has a fragment
        # there (the per-shard walk returns early otherwise) — compact the
        # stacks to that intersection so sparse fields stay cheap.
        gb_shards = [
            s
            for s in shard_list
            if all(v.fragment_if_exists(s) is not None for v in child_views)
        ]
        if not gb_shards:
            return {}
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        low = _StackedLowering(self, idx, gb_shards, no_sparse_guard=True)
        planes_list = []
        try:
            with DEVICE_CACHE.deferred_eviction():
                filt = None
                if filter_call is not None:
                    root = low.lower(filter_call)
                    if isinstance(root, PZero) or not low.operands:
                        return {}  # filter matches nothing anywhere
                    filt = StackedPlan(
                        root, low.operands, low.scalars, len(gb_shards)
                    ).rows_full()
                for v, rows in zip(child_views, child_rows):
                    low._stack_guard(v, mult=max(len(rows), 1))
                    p = v.plane_stack(rows, low.shards)
                    if p is None:
                        return {}
                    planes_list.append(p)
        except Unsupported:
            return None
        finally:
            low.extents.release()  # staging-window pins (see _stacked_bsi)
        from pilosa_tpu.exec import groupby as qgb
        from pilosa_tpu.exec import plan as planmod

        # the whole cross-tally pipeline (multiple dispatches + reads over
        # mesh-sharded plane stacks) runs as one serialized occupancy of
        # the device — concurrent GroupBy legs from other in-process nodes
        # must not interleave collective-bearing programs (plan.run_serialized
        # rationale); operands above were staged before entry
        with planmod.dispatch_mutex():
            return qgb.group_by_device(planes_list, child_rows, filt)

    def _group_by_shard(  # dispatch-ok: per-shard path, single-device
        self, idx, child_fields, child_rows, filter_words, shard, merged
    ) -> None:
        """Nested cross-product with zero-count pruning (the reference's
        groupByIterator, executor.go:3063)."""
        frags = []
        for fname in child_fields:
            f = self._field_of(idx, fname)
            v = f.view(VIEW_STANDARD)
            frag = v.fragment_if_exists(shard) if v is not None else None
            if frag is None:
                return
            frags.append(frag)

        def recurse(depth: int, acc_words, prefix: Tuple[int, ...]):
            frag = frags[depth]
            ids = [r for r in child_rows[depth] if frag.has_row(r)]
            if not ids:
                return
            counts = frag.row_counts(ids, acc_words)
            for rid, cnt in zip(ids, counts):
                if cnt == 0:
                    continue
                key = prefix + (rid,)
                if depth == len(frags) - 1:
                    merged[key] = merged.get(key, 0) + int(cnt)
                else:
                    words = frag.row_device(rid)
                    nxt = words if acc_words is None else ob.b_and(acc_words, words)
                    recurse(depth + 1, nxt, key)

        recurse(0, filter_words, ())

    # ------------------------------------------------------------------
    # Options (executor.go:360)
    # ------------------------------------------------------------------

    def _execute_options(self, idx: Index, c: Call, shards, opt: ExecOptions):
        if len(c.children) != 1:
            raise ExecError("Options() requires a single child query")
        new_opt = ExecOptions(
            remote=opt.remote,
            exclude_row_attrs=bool(c.args.get("excludeRowAttrs", opt.exclude_row_attrs)),
            exclude_columns=bool(c.args.get("excludeColumns", opt.exclude_columns)),
            column_attrs=bool(c.args.get("columnAttrs", opt.column_attrs)),
            max_writes=opt.max_writes,
        )
        # columnAttrs is read at response level, so it must propagate to the
        # caller's options (reference mutates the shared opt, executor.go:368)
        opt.column_attrs = new_opt.column_attrs
        s = c.args.get("shards")
        if s is not None:
            if not isinstance(s, list):
                raise ExecError("Options() shards must be a list")
            shards = [int(x) for x in s]
        return self._execute_call(idx, c.children[0], shards, new_opt)
