"""Query key translation: string keys in calls <-> uint64 ids in results.

Reference: /root/reference/executor.go:2615-2912 (translateCalls /
translateResults) — before execution, every string key in the AST is
replaced by its uint64 id via the index's column TranslateStore or the
field's row TranslateStore; after execution, ids in results are mapped back
to keys when the index/field has keys enabled.

Translation allocates ids on demand (the reference's
TranslateColumnsToUint64 allocates for both reads and writes — a read of a
never-seen key yields a fresh id whose row/column is empty, so results are
unchanged). Allocation is host-side and never touches the device path.
"""

from __future__ import annotations

from typing import Any, List

from pilosa_tpu.core.index import Index
from pilosa_tpu.pql.ast import Call, Query

class TranslationError(Exception):
    pass


def translate_call(idx: Index, c: Call) -> None:
    """In-place key->id translation of one call tree."""
    # column keys (Set(col, ...), SetColumnAttrs(col, ...), Row(_col=...) n/a)
    col = c.args.get("_col")
    if isinstance(col, str):
        if not idx.keys:
            raise TranslationError(
                f"string column key {col!r} requires index keys=true"
            )
        c.args["_col"] = idx.translate_store.translate_key(col)
    elif col is not None and idx.keys and not isinstance(col, bool):
        # integer column on a keyed index is an error in the reference
        raise TranslationError("column value must be a string when index keys are on")

    # row keys via _row + _field (ClearRow/Store/SetRowAttrs forms)
    row = c.args.get("_row")
    if isinstance(row, str):
        fname = c.args.get("_field")
        f = idx.field(fname) if fname else None
        if f is None or not f.options.keys:
            raise TranslationError(
                f"string row key {row!r} requires field keys=true"
            )
        c.args["_row"] = f.translate_store.translate_key(row)

    # row keys via field-named args: Row(f="key"), Set(c, f="key"), ...
    for k in list(c.args):
        if k.startswith("_") or k in ("from", "to"):
            continue
        v = c.args[k]
        if not isinstance(v, str):
            continue
        f = idx.field(k)
        if f is None:
            continue
        if not f.options.keys:
            raise TranslationError(
                f"string row key {v!r} requires field {k!r} keys=true"
            )
        c.args[k] = f.translate_store.translate_key(v)

    # GroupBy(previous=[...]) pagination cursor: one entry per child Rows
    # call; string entries translate through that child's field row keys
    # (reference executor.go:2742-2782).
    if c.name == "GroupBy":
        gprev = c.args.get("previous")
        if gprev is not None:
            if not isinstance(gprev, list):
                raise TranslationError(
                    f"'previous' argument must be list, but got {type(gprev).__name__}"
                )
            if len(gprev) != len(c.children):
                raise TranslationError(
                    f"mismatched lengths for previous: {len(gprev)} and "
                    f"children: {len(c.children)}"
                )
            for i, pv in enumerate(gprev):
                child = c.children[i]
                fname = child.string_arg("field") or child.args.get("_field")
                f = idx.field(fname) if fname else None
                if f is not None and f.options.keys:
                    if not isinstance(pv, str):
                        raise TranslationError(
                            "prev value must be a string when field 'keys' option enabled"
                        )
                    gprev[i] = f.translate_store.translate_key(pv)
                elif isinstance(pv, str):
                    raise TranslationError(
                        f"got string row val {pv!r} in 'previous' for field "
                        f"{fname} which doesn't use string keys"
                    )

    # Rows(previous="key") pagination cursor
    prev = c.args.get("previous")
    if isinstance(prev, str) and c.name != "GroupBy":
        fname = c.args.get("field") or c.args.get("_field")
        f = idx.field(fname) if fname else None
        if f is None or not f.options.keys:
            raise TranslationError("Rows(previous=<key>) requires field keys=true")
        c.args["previous"] = f.translate_store.translate_key(prev)

    # Rows(column="key") / GroupBy filter columns
    colarg = c.args.get("column")
    if isinstance(colarg, str):
        if not idx.keys:
            raise TranslationError("string column key requires index keys=true")
        c.args["column"] = idx.translate_store.translate_key(colarg)

    # nested calls in args (e.g. GroupBy filter=<call>)
    for v in c.args.values():
        if isinstance(v, Call):
            translate_call(idx, v)
    for child in c.children:
        translate_call(idx, child)


def translate_query(idx: Index, q: Query) -> None:
    for c in q.calls:
        translate_call(idx, c)


def translate_result(idx: Index, c: Call, result: Any) -> Any:
    """Id->key translation of one call's result (reference:
    translateResults, executor.go:2786)."""
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.exec.executor import FieldRow, GroupCount, Pair

    if isinstance(result, Row):
        if idx.keys:
            result.keys = [
                idx.translate_store.key_for_id(int(c_)) or ""
                for c_ in result.columns().tolist()
            ]
        return result

    if isinstance(result, list) and result and isinstance(result[0], Pair):
        fname = c.args.get("_field") or c.string_arg("field")
        f = idx.field(fname) if fname else None
        if f is not None and f.options.keys:
            for p in result:
                p.key = f.translate_store.key_for_id(p.id)
        return result

    if isinstance(result, list) and result and isinstance(result[0], GroupCount):
        for gc in result:
            for fr in gc.group:
                f = idx.field(fr.field)
                if f is not None and f.options.keys:
                    fr.row_key = f.translate_store.key_for_id(fr.row_id)
        return result

    # Rows() -> list of row ids
    if (
        c.name == "Rows"
        and isinstance(result, list)
        and (not result or isinstance(result[0], int))
    ):
        fname = c.string_arg("field") or c.args.get("_field")
        f = idx.field(fname) if fname else None
        if f is not None and f.options.keys:
            return [f.translate_store.key_for_id(r) for r in result]
        return result

    return result


def translate_results(idx: Index, q: Query, results: List[Any]) -> List[Any]:
    return [translate_result(idx, c, r) for c, r in zip(q.calls, results)]
