"""Cross-request count batching: group-commit coalescing of concurrent
Count queries into one multi-root device dispatch.

The executor already folds adjacent Count calls *within* one PQL request
into a single MultiCountPlan dispatch (exec/plan.py). This module extends
that amortization *across requests*: concurrent clients each issuing a
single Count pay ~one dispatch+read between all of them instead of one
each — on tunneled hardware that is the difference between N x RTT and
~RTT + N x device-time.

Group-commit (not a timer window): the first arriving query executes
immediately as the leader — an idle server adds ZERO latency. Queries
arriving while the leader's dispatch is in flight queue up; when the
leader finishes, the whole queue executes as one merged multi-Count
request, slicing results back per caller. Batch size adapts to load
(arrival rate x dispatch time), the way group commit batches WAL writers.
The reference instead bounds per-request fan-out with a worker pool
(reference: executor.go:2559-2613 mapReduce + shard worker pool) and
gives concurrent requests no cross-request amortization at all.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

from pilosa_tpu.utils.locks import TrackedLock
from pilosa_tpu.pql import Query

# Bound on calls merged into one execution: keeps lowered plan shapes in a
# small family (compile cache) and bounds result-slicing latency for the
# earliest waiter under pathological fan-in.
MAX_BATCH_CALLS = 64

STATS = {"leader": 0, "batched": 0, "merged_execs": 0, "fallback_splits": 0}
_STATS_MU = TrackedLock("batcher.stats_mu")


def _bump(key: str) -> None:
    # '+=' from concurrent request threads loses increments across GIL
    # preemption; tests assert exact totals
    with _STATS_MU:
        STATS[key] += 1


def batchable(query: Query) -> bool:
    """Only plain read Counts merge: every call `Count(<one child>)`."""
    return bool(query.calls) and all(
        c.name == "Count" and len(c.children) == 1 for c in query.calls
    )


class _Waiter:
    __slots__ = ("query", "event", "results", "error", "promoted")

    def __init__(self, query: Query):
        self.query = query
        self.event = threading.Event()
        self.results = None
        self.error = None
        self.promoted = False  # woken to take over leadership


class CountBatcher:
    """Per-index group-commit batcher. `execute` is called with a merged
    Query and must return one result per call (the api layer binds it to
    executor.execute_response).

    Leadership is bounded and handed off: a leader executes its own query,
    serves ONE snapshot of the waiters that queued behind it, then — if
    new waiters arrived meanwhile — promotes the first of them to leader
    instead of looping. Under sustained load every client therefore waits
    at most ~two service rounds; the first arriver is never stuck serving
    everyone else's queries forever."""

    def __init__(self):
        self._mu = TrackedLock("batcher.mu")
        self._busy: Dict[str, bool] = {}
        self._queue: Dict[str, List[_Waiter]] = {}

    def run(self, index: str, query: Query, execute: Callable[[Query], list]):
        with self._mu:
            if self._busy.get(index):
                w = _Waiter(query)
                self._queue.setdefault(index, []).append(w)
            else:
                self._busy[index] = True
                w = None
        if w is not None:
            w.event.wait()
            if w.promoted:
                # took over leadership: this thread executes the next
                # round MERGED WITH ITS OWN QUERY (a solo promoted leader
                # would make every other round a batch of one under
                # sustained load), then hands off again
                _bump("leader")
                self._serve_round(index, execute, first=w)
            else:
                _bump("batched")
            if w.error is not None:
                raise w.error
            return w.results
        return self._lead(index, query, execute)

    # -- internals ---------------------------------------------------------

    def _lead(self, index: str, query: Query, execute):
        _bump("leader")
        try:
            return execute(query)
        finally:
            self._serve_round(index, execute)

    def _serve_round(self, index: str, execute, first: "_Waiter" = None) -> None:
        """Serve the waiters present right now (in MAX_BATCH_CALLS-sized
        merges, `first` prepended when a promoted leader brings its own
        query), then hand leadership to the first later arrival — or
        release the slot when the queue is empty."""
        with self._mu:
            round_ = self._queue.get(index, [])
            self._queue[index] = []
        if first is not None:
            round_.insert(0, first)
        while round_:
            batch: List[_Waiter] = []
            n = 0
            while round_ and n + len(round_[0].query.calls) <= MAX_BATCH_CALLS:
                wtr = round_.pop(0)
                batch.append(wtr)
                n += len(wtr.query.calls)
            if not batch:  # single oversized query: run it alone
                batch = [round_.pop(0)]
            self._run_batch(batch, execute)
        with self._mu:
            queued = self._queue.get(index)
            if queued:
                nxt = queued.pop(0)
                nxt.promoted = True
                nxt.event.set()  # takes over; _busy stays held
            else:
                self._queue.pop(index, None)
                self._busy.pop(index, None)

    @staticmethod
    def _run_batch(batch: List[_Waiter], execute) -> None:
        if len(batch) == 1:
            w = batch[0]
            try:
                w.results = execute(w.query)
            except Exception as e:  # noqa: BLE001 - delivered to the waiter
                w.error = e
            w.event.set()
            return
        calls = [c for w in batch for c in w.query.calls]
        # pad to a pow2 call count (repeat the last call; extras dropped):
        # the multi-root plan compiles once per size family instead of once
        # per distinct batch size
        n_real = len(calls)
        target = 1 << max(n_real - 1, 0).bit_length()
        calls = calls + [calls[-1]] * (target - n_real)
        merged = Query(calls=calls)
        try:
            _bump("merged_execs")
            res = execute(merged)
            k = 0
            for w in batch:
                n = len(w.query.calls)
                w.results = res[k : k + n]
                k += n
                w.event.set()
        except Exception:
            # error isolation: one bad query must not fail its batchmates
            _bump("fallback_splits")
            for w in batch:
                try:
                    w.results = execute(w.query)
                except Exception as e:  # noqa: BLE001
                    w.error = e
                w.event.set()
