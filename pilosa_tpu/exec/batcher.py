"""Cross-request count batching: group-commit coalescing of concurrent
Count queries into one multi-root device dispatch.

The executor already folds adjacent Count calls *within* one PQL request
into a single MultiCountPlan dispatch (exec/plan.py). This module extends
that amortization *across requests*: concurrent clients each issuing a
single Count pay ~one dispatch+read between all of them instead of one
each — on tunneled hardware that is the difference between N x RTT and
~RTT + N x device-time.

Group-commit (not a timer window): the first arriving query executes
immediately as the leader — an idle server adds ZERO latency. Queries
arriving while the leader's dispatch is in flight queue up; when the
leader finishes, the whole queue executes as one merged multi-Count
request, slicing results back per caller. Batch size adapts to load
(arrival rate x dispatch time), the way group commit batches WAL writers.
The reference instead bounds per-request fan-out with a worker pool
(reference: executor.go:2559-2613 mapReduce + shard worker pool) and
gives concurrent requests no cross-request amortization at all.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from pilosa_tpu.utils import tracing
from pilosa_tpu.utils.locks import TrackedCondition, TrackedLock
from pilosa_tpu.utils.race import race_checked
from pilosa_tpu.pql import Call, Query


def _noop_pad_call() -> Call:
    """Zero-row no-op lane for pow2 padding: `Count(Difference())` lowers
    to a PZero root — an all-zero stack that adds no operand reads and no
    meaningful device work — unlike repeating the batch's last call, which
    re-ran real (possibly heavy) device work for every pad lane (up to
    ~2x waste on odd batch sizes). Pad results are masked out of the
    per-waiter slices by construction (slicing stops at the real calls)."""
    return Call(name="Count", children=[Call(name="Difference")])

# Bound on calls merged into one execution: keeps lowered plan shapes in a
# small family (compile cache) and bounds result-slicing latency for the
# earliest waiter under pathological fan-in.
MAX_BATCH_CALLS = 64

STATS = {"leader": 0, "batched": 0, "merged_execs": 0, "fallback_splits": 0}
_STATS_MU = TrackedLock("batcher.stats_mu")


def _bump(key: str) -> None:
    # '+=' from concurrent request threads loses increments across GIL
    # preemption; tests assert exact totals
    with _STATS_MU:
        STATS[key] += 1


def batchable(query: Query) -> bool:
    """Only plain read Counts merge: every call `Count(<one child>)`."""
    return bool(query.calls) and all(
        c.name == "Count" and len(c.children) == 1 for c in query.calls
    )


def batch_eligible(query, shards, opt) -> bool:
    """Will this request be ROUTED through the batcher? The single
    source of truth shared by api._query_batched (routing) and
    api._admit (the adaptive-batching load hint) — two copies of this
    condition would silently diverge and mis-size the hint."""
    return (
        shards is None
        and not opt.remote
        and not opt.column_attrs
        and not opt.exclude_row_attrs
        and not opt.exclude_columns
        and isinstance(query, Query)
        and batchable(query)
    )


class _Waiter:
    __slots__ = ("query", "event", "results", "error", "promoted", "cls")

    def __init__(self, query: Query, cls=None):
        self.query = query
        self.event = threading.Event()
        self.results = None
        self.error = None
        self.promoted = False  # woken to take over leadership
        # lowering class (CountBatcher.classify): queries of different
        # classes must not merge into one multi-root plan — a mesh-group
        # Count's sharded operands and an extent-path Count's local
        # stacks have incompatible placements
        self.cls = cls


@race_checked(exclude=(
    # wired once by NodeServer between construction and serving (init-
    # before-publish handoff); hold_timeout is a test/operator knob
    "load_hint",
    "hold_timeout",
    "stats",
    "classify",
))
class CountBatcher:
    """Per-index group-commit batcher. `execute` is called with a merged
    Query and must return one result per call (the api layer binds it to
    executor.execute_response).

    Leadership is bounded and handed off: a leader executes its own query,
    serves ONE snapshot of the waiters that queued behind it, then — if
    new waiters arrived meanwhile — promotes the first of them to leader
    instead of looping. Under sustained load every client therefore waits
    at most ~two service rounds; the first arriver is never stuck serving
    everyone else's queries forever."""

    def __init__(self):
        self._mu = TrackedLock("batcher.mu")
        # signalled whenever a waiter enqueues; the adaptive leader hold
        # (see run()) sleeps on it instead of polling
        self._arrived = TrackedCondition(self._mu, name="batcher.arrived")
        self._busy: Dict[str, bool] = {}
        self._queue: Dict[str, Deque[_Waiter]] = {}
        # -- adaptive batching (sched/ admission feeds this) --------------
        # load_hint(index) returns the number of BATCHABLE queries for
        # `index` currently admitted or queued by the admission
        # controller — i.e. actual potential batch mates. When it
        # reports load, a fresh leader HOLDS its dispatch briefly
        # (hold_timeout) until that many calls have accumulated, so batch
        # size tracks queue depth (the >=4-queries/sweep plateau from
        # BENCH_NOTES r3) instead of relying on dispatch-overlap luck.
        self.load_hint: Optional[Callable[[str], int]] = None
        self.hold_timeout: float = 0.005  # seconds; bounds added latency
        # stats client (NodeServer wires its own); emits one
        # `batcher.batch_size` observation per executed round
        self.stats = None
        # lowering-class hook: classify(index, query) -> hashable key.
        # Rounds are executed per class — a merged multi-root plan must
        # never mix mesh-group and extent-path Counts (incompatible
        # operand placements). None = one class for everything (the
        # single-node default). Must never raise for a valid query; a
        # failure degrades to the shared default class.
        self.classify: Optional[Callable[[str, Query], object]] = None

    def _class_of(self, index: str, query: Query):
        if self.classify is None:
            return None
        try:
            return self.classify(index, query)
        except Exception:  # noqa: BLE001 - classification is advisory
            return None

    def run(self, index: str, query: Query, execute: Callable[[Query], list]):
        cls = self._class_of(index, query)
        with self._mu:
            if self._busy.get(index):
                w = _Waiter(query, cls)
                self._queue.setdefault(index, deque()).append(w)
                self._arrived.notify_all()
            else:
                self._busy[index] = True
                w = None
        if w is not None:
            t_wait0 = time.monotonic()
            w.event.wait()
            if w.promoted:
                # took over leadership: this thread executes the next
                # round MERGED WITH ITS OWN QUERY (a solo promoted leader
                # would make every other round a batch of one under
                # sustained load), then hands off again
                _bump("leader")
                self._serve_round(index, execute, first=w)
            else:
                _bump("batched")
            # flight record: this query rode along in someone else's
            # round — the wait (and, when promoted, the round it then
            # led) is where its milliseconds went
            tracing.record_span(
                "exec.batch",
                time.monotonic() - t_wait0,
                tags={
                    "batcher.role": "promoted" if w.promoted else "batched",
                },
            )
            if w.error is not None:
                raise w.error
            return w.results
        # leadership taken: only NOW consult the scheduler's load hint —
        # followers and promoted leaders never read it, so the hot path
        # pays the (locked) hint lookup once per round, not per call
        target = 0
        if self.load_hint is not None:
            try:
                target = min(int(self.load_hint(index)), MAX_BATCH_CALLS)
            except Exception:  # noqa: BLE001 - a hint must never fail a query
                target = 0
        if target >= 2:
            # adaptive hold: the admission controller reports `target`
            # queries in flight/queued — wait (bounded) for them to line
            # up behind us, then run the whole set as ONE merged dispatch
            lead = _Waiter(query, cls)
            deadline = time.monotonic() + self.hold_timeout
            with self._mu:
                # target counts QUERIES (the admission hint's unit), so
                # the lined-up side counts queries too — comparing calls
                # against a query target would end the hold early for
                # any multi-call leader
                while 1 + len(self._queue.get(index, ())) < target:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._arrived.wait(remaining)
            _bump("leader")
            self._serve_round(index, execute, first=lead)
            if lead.error is not None:
                raise lead.error
            return lead.results
        return self._lead(index, query, execute)

    # -- internals ---------------------------------------------------------

    def _lead(self, index: str, query: Query, execute):
        _bump("leader")
        self._record_round(len(query.calls))
        try:
            with tracing.start_span("exec.batch") as sp:
                sp.set_tag("batcher.role", "leader")
                sp.set_tag("batcher.calls", len(query.calls))
                return execute(query)
        finally:
            self._serve_round(index, execute)

    def _serve_round(self, index: str, execute, first: "_Waiter" = None) -> None:
        """Serve the waiters present right now (in MAX_BATCH_CALLS-sized
        merges, `first` prepended when a promoted leader brings its own
        query), then hand leadership to the first later arrival — or
        release the slot when the queue is empty.

        Merges are split BY LOWERING CLASS (self.classify): a round mixing
        mesh-group and fan-out/extent Counts executes as one sub-batch per
        class in arrival order — one merged multi-root plan must never mix
        operand placements."""
        with self._mu:
            round_ = self._queue.get(index) or deque()
            self._queue[index] = deque()
        if first is not None:
            round_.appendleft(first)
        # partition by class, preserving arrival order within each
        by_cls: Dict[object, Deque[_Waiter]] = {}
        order: List[object] = []
        for wtr in round_:
            if wtr.cls not in by_cls:
                by_cls[wtr.cls] = deque()
                order.append(wtr.cls)
            by_cls[wtr.cls].append(wtr)
        for cls in order:
            bucket = by_cls[cls]
            while bucket:
                batch: List[_Waiter] = []
                n = 0
                while bucket and n + len(bucket[0].query.calls) <= MAX_BATCH_CALLS:
                    wtr = bucket.popleft()
                    batch.append(wtr)
                    n += len(wtr.query.calls)
                if not batch:  # single oversized query: run it alone
                    batch = [bucket.popleft()]
                self._run_batch(batch, execute)
        with self._mu:
            queued = self._queue.get(index)
            if queued:
                nxt = queued.popleft()
                nxt.promoted = True
                nxt.event.set()  # takes over; _busy stays held
            else:
                self._queue.pop(index, None)
                self._busy.pop(index, None)

    def _record_round(self, n_calls: int) -> None:
        """One executed round's size — the observable the scheduler's
        adaptive hook is judged by (>=4 under load, BENCH_NOTES r3)."""
        if self.stats is not None:
            self.stats.histogram("batcher.batch_size", float(n_calls))

    def _run_batch(self, batch: List[_Waiter], execute) -> None:
        if len(batch) == 1:
            w = batch[0]
            self._record_round(len(w.query.calls))
            try:
                w.results = execute(w.query)
            except Exception as e:  # noqa: BLE001 - delivered to the waiter
                w.error = e
            w.event.set()
            return
        calls = [c for w in batch for c in w.query.calls]
        self._record_round(len(calls))
        # pad to a pow2 call count with zero-row no-op lanes (masked out
        # of results by the per-waiter slicing below): the multi-root plan
        # compiles once per size family instead of once per distinct
        # batch size, and the pad lanes cost ~no device work
        n_real = len(calls)
        target = 1 << max(n_real - 1, 0).bit_length()
        calls = calls + [_noop_pad_call() for _ in range(target - n_real)]
        merged = Query(calls=calls)
        try:
            _bump("merged_execs")
            with tracing.start_span("exec.batch") as sp:
                sp.set_tag("batcher.role", "merged-leader")
                sp.set_tag("batcher.calls", n_real)
                res = execute(merged)
            k = 0
            for w in batch:
                n = len(w.query.calls)
                w.results = res[k : k + n]
                k += n
                w.event.set()
        except Exception:
            # error isolation: one bad query must not fail its batchmates
            _bump("fallback_splits")
            for w in batch:
                try:
                    w.results = execute(w.query)
                except Exception as e:  # noqa: BLE001
                    w.error = e
                w.event.set()
