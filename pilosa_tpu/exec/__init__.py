from pilosa_tpu.exec.executor import Executor, ExecOptions, GroupCount, Pair, ValCount  # noqa: F401
